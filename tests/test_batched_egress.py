"""Batched egress pipeline (ISSUE 10): response-path batching — the
per-destination flush accumulator (runtime.egress), the header-prefix
wire template (hotwire.c make_header_template/pack_batch_tmpl), the
batched client-side correlation (receive_response_batch), per-caller
FIFO, pool discipline, tracing parity, and the EGRESS_STATS stages."""

import asyncio
import time
from types import SimpleNamespace

import numpy as np
import pytest

import orleans_tpu.core.serialization as ser
from orleans_tpu.core.ids import GrainId, GrainType, SiloAddress
from orleans_tpu.core.message import (Direction, Message, RejectionType,
                                      ResponseKind, make_error_response,
                                      make_rejection, make_request,
                                      make_response, pool_generation,
                                      recycle_messages, set_debug_pool)
from orleans_tpu.observability.stats import EGRESS_STATS
from orleans_tpu.runtime import Grain, SiloBuilder
from orleans_tpu.runtime.egress import EgressBatcher
from orleans_tpu.runtime.runtime_client import (RuntimeClient,
                                                _fresh_callback)
from orleans_tpu.runtime.wire import (decode_frames, encode_message,
                                      encode_message_batch)

hw = ser._hotwire

GT = GrainType.of("eg.Echo")
S1 = SiloAddress("10.9.0.1", 1111, 3)
S2 = SiloAddress("10.9.0.2", 2222, 5)


def _response_corpus(n: int = 36) -> list:
    """Responses with the header variety the template must carry —
    traced (TRACE_KEY stamps), txn-join piggybacks, errors — plus the
    headers that must PEEL (rejections), interleaved with requests.
    ``timeout=None`` keeps TTLs out so two encodes are byte-identical."""
    out = []
    for i in range(n):
        req = make_request(
            target_grain=GrainId.for_grain(GT, i),
            interface_name="eg.IEcho", method_name=f"m{i % 4}",
            body=((i,), {}), sending_silo=S2, target_silo=S1,
            timeout=None)
        if i % 9 == 0:
            resp = make_rejection(req, RejectionType.TRANSIENT, "stale")
        elif i % 5 == 0:
            resp = make_error_response(req, ValueError(f"boom-{i}"))
        else:
            resp = make_response(req, {"r": i, "blob": b"x" * (i % 7)})
        if i % 4 == 0:
            # sampled response: the _stamp_response wall stamp rides the
            # varying request_context field of the template
            resp.request_context = {
                "__otpu_trace__": (0xABC0 + i, i, 1700000000.0 + i)}
        if i % 6 == 0:
            resp.transaction_info = (i, {i: "participant"})
        resp.target_silo = req.sending_silo
        out.append(resp)
        if i % 3 == 0:
            out.append(req)  # mixed run: requests interleave
    return out


def _slots_equal(a: Message, b: Message) -> bool:
    for s in Message.__slots__:
        if s in ("received_at", "_pool_free", "_pool_gen", "expires_at"):
            continue
        va, vb = getattr(a, s), getattr(b, s)
        if isinstance(va, BaseException) or isinstance(vb, BaseException):
            # exceptions never compare equal instance-wise: type + args
            # is what the wire round-trip preserves
            if type(va) is not type(vb) or va.args != vb.args:
                return False
            continue
        if va != vb:
            return False
    return True


# ---------------------------------------------------------------------------
# Codec property: template batch bytes == per-frame bytes
# ---------------------------------------------------------------------------

@pytest.mark.skipif(hw is None, reason="native toolchain unavailable")
def test_template_batch_bytes_identical_to_per_frame():
    msgs = _response_corpus()
    per_frame = b"".join(encode_message(m) for m in msgs)
    chunks = encode_message_batch(msgs, bounce=lambda m, e: None)
    assert b"".join(chunks) == per_frame
    # the template actually engaged: templated response runs split the
    # output into more than one chunk (requests/rejections peel)
    assert len(chunks) > 1
    # and the A/B lever's encoder produces the same bytes
    plain = encode_message_batch(msgs, bounce=lambda m, e: None,
                                 templates=False)
    assert b"".join(plain) == per_frame


@pytest.mark.skipif(hw is None, reason="native toolchain unavailable")
def test_template_batch_decodes_slot_identical():
    msgs = [m for m in _response_corpus() if True]
    buf = bytearray(b"".join(
        encode_message_batch(msgs, bounce=lambda m, e: None)))
    consumed, decoded, bounces = decode_frames(buf)
    assert consumed == len(buf) and not bounces
    assert len(decoded) == len(msgs)
    for got, orig in zip(decoded, msgs):
        assert _slots_equal(got, orig)


def _request_corpus(n: int = 40) -> list:
    """call_batch-shaped REQUEST batches plus the variety the request
    template must carry: one-ways, traced request_context, in-grain
    senders with a non-empty call chain, interleaved responses, and the
    headers that must PEEL (forwarded/resent requests)."""
    from orleans_tpu.core.message import make_request_fast
    from orleans_tpu.core.message import Category
    chain = (GrainId.for_grain(GT, 999),)
    out = []
    for i in range(n):
        d = Direction.ONE_WAY if i % 7 == 0 else Direction.REQUEST
        ctx = ({"__otpu_trace__": (0xD0 + i, i, 1700000000.0 + i)}
               if i % 4 == 0 else ({"bag": i} if i % 5 == 0 else None))
        m = make_request_fast(
            Category.APPLICATION, d, S2, None, None, S1,
            GrainId.for_grain(GT, i), "eg.IEcho", f"m{i % 3}",
            ((), {"x": i}), None,
            chain if i % 3 == 0 else (), i % 2 == 0, False, ctx, i % 2)
        if i % 11 == 0:
            m.forward_count = 1  # must peel
        out.append(m)
        if i % 6 == 0:
            req = make_request(
                target_grain=GrainId.for_grain(GT, i),
                interface_name="eg.IEcho", method_name="m",
                body=((i,), {}), sending_silo=S1, target_silo=S2,
                timeout=None)
            resp = make_response(req, i)
            resp.target_silo = S2
            out.append(resp)  # mixed run: responses interleave
    return out


@pytest.mark.skipif(hw is None, reason="native toolchain unavailable")
def test_request_template_batch_bytes_identical_to_per_frame():
    """The request-side header-prefix template (the call_batch native
    sender half): batch bytes == concatenated per-frame bytes across
    one-ways, traced headers, chain-carrying senders, and peels."""
    msgs = _request_corpus()
    per_frame = b"".join(encode_message(m) for m in msgs)
    chunks = encode_message_batch(msgs, bounce=lambda m, e: None)
    assert b"".join(chunks) == per_frame
    assert len(chunks) > 1  # template/plain runs actually split
    plain = encode_message_batch(msgs, bounce=lambda m, e: None,
                                 templates=False)
    assert b"".join(plain) == per_frame
    # round trip: every header slot survives the template encode
    consumed, decoded, bounces = decode_frames(
        bytearray(b"".join(chunks)))
    assert consumed == len(per_frame) and not bounces
    assert len(decoded) == len(msgs)
    for got, orig in zip(decoded, msgs):
        assert _slots_equal(got, orig)


def test_pickle_fallback_path_unchanged(monkeypatch):
    """ORLEANS_TPU_NATIVE=0 form: no template machinery, per-frame
    chunks, same decodable bytes."""
    msgs = _response_corpus(12)
    monkeypatch.setattr(ser, "_hotwire", None)
    chunks = encode_message_batch(msgs, bounce=lambda m, e: None)
    assert len(chunks) == len(msgs)
    consumed, decoded, _ = decode_frames(bytearray(b"".join(chunks)))
    assert len(decoded) == len(msgs)
    assert all(_slots_equal(g, o) for g, o in zip(decoded, msgs))


@pytest.mark.skipif(hw is None, reason="native toolchain unavailable")
def test_template_peels_headers_it_cannot_carry():
    """Rejections, forwarded and chain-carrying responses must NOT ride
    the template (their headers fall outside the invariant constants) —
    and must still encode byte-identically via the per-frame run."""
    from orleans_tpu.runtime.wire import _frame_template

    req = make_request(target_grain=GrainId.for_grain(GT, 1),
                       interface_name="eg.IEcho", method_name="m",
                       body=((), {}), sending_silo=S2, target_silo=S1,
                       timeout=None)
    ok = make_response(req, 1)
    ok.target_silo = S2
    assert _frame_template(ok) is not None
    rej = make_rejection(req, RejectionType.OVERLOADED, "busy")
    rej.target_silo = S2
    assert _frame_template(rej) is None
    fwd = make_response(req, 1)
    fwd.target_silo = S2
    fwd.forward_count = 1
    assert _frame_template(fwd) is None
    chained = make_response(req, 1)
    chained.target_silo = S2
    chained.call_chain = (GrainId.for_grain(GT, 2),)
    assert _frame_template(chained) is None
    # requests template too since the call_batch sender half landed —
    # but a forwarded request still peels
    assert _frame_template(req) is not None
    fwd_req = make_request(target_grain=GrainId.for_grain(GT, 3),
                           interface_name="eg.IEcho", method_name="m",
                           body=((), {}), sending_silo=S2, target_silo=S1,
                           timeout=None)
    fwd_req.forward_count = 1
    assert _frame_template(fwd_req) is None
    batch = [ok, rej, fwd, chained]
    chunks = encode_message_batch(batch, bounce=lambda m, e: None)
    assert b"".join(chunks) == b"".join(encode_message(m) for m in batch)


# ---------------------------------------------------------------------------
# The flush accumulator
# ---------------------------------------------------------------------------

def _fake_center(metrics: bool = False):
    from orleans_tpu.observability.stats import StatsRegistry
    sent = []
    stats = StatsRegistry() if metrics else None
    center = SimpleNamespace(
        silo=SimpleNamespace(ingest_stats=stats),
        send_batch=lambda dest, msgs: sent.append((dest, list(msgs))))
    return center, sent


async def test_accumulator_groups_per_destination_one_flush():
    center, sent = _fake_center()
    eg = EgressBatcher(center)
    msgs = _response_corpus(8)
    for i, m in enumerate(msgs):
        eg.add(S1 if i % 2 else S2, m)
    assert not sent  # armed, not flushed: nothing handed off yet
    await asyncio.sleep(0)  # the armed call_soon flush runs
    assert len(sent) == 2   # ONE send_batch per destination
    assert sorted(len(g) for _, g in sent) == [len(msgs) // 2,
                                               (len(msgs) + 1) // 2]
    assert not eg.groups and eg.last_group > 0


async def test_flush_dest_is_the_fifo_guard():
    center, sent = _fake_center()
    eg = EgressBatcher(center)
    msgs = _response_corpus(4)
    eg.add(S1, msgs[0])
    eg.add(S2, msgs[1])
    eg.flush_dest(S1)           # a per-message send to S1 drains S1 only
    assert sent == [(S1, [msgs[0]])]
    await asyncio.sleep(0)      # the armed flush still drains S2
    assert sent[1][0] == S2 and sent[1][1] == [msgs[1]]


async def test_system_and_ping_responses_bypass_accumulator():
    """PING/SYSTEM responses (membership probes, control RPCs) must take
    the per-message path: the accumulator's end-of-ready-run flush can
    sit behind a saturated loop's whole callback run, and a probe
    response delayed past the probe timeout gets a healthy silo voted
    dead (observed as a false-death spiral in the chaos soak)."""
    from orleans_tpu.core.message import Category
    from orleans_tpu.runtime.cluster import InProcFabric

    class Echo(Grain):
        async def ping(self):
            return 1

    fabric = InProcFabric()
    silo = (SiloBuilder().with_fabric(fabric).add_grains(Echo)).build()
    fabric.is_dead = lambda a: False
    sent = []
    fabric.deliver_group = lambda dest, msgs: sent.append(("group", dest))
    fabric.deliver = lambda msg: sent.append(("single", msg.category))
    for cat in (Category.PING, Category.SYSTEM):
        req = make_request(target_grain=GrainId.for_grain(GT, 1),
                          interface_name="Echo", method_name="ping",
                          body=((), {}), sending_silo=S2, target_silo=S1,
                          category=cat)
        silo.dispatcher.send_response(req, make_response(req, 1))
    assert not silo.message_center.egress.groups
    assert sent == [("single", Category.PING), ("single", Category.SYSTEM)]
    # APPLICATION responses still accumulate
    req = make_request(target_grain=GrainId.for_grain(GT, 2),
                      interface_name="Echo", method_name="ping",
                      body=((), {}), sending_silo=S2, target_silo=S1)
    silo.dispatcher.send_response(req, make_response(req, 2))
    assert silo.message_center.egress.groups


async def test_send_message_drains_pending_group_for_fifo():
    """MessageCenter.send_message must flush a pending response group to
    its destination before the per-message send — per-sender FIFO per
    target is the wire's one ordering guarantee."""
    from orleans_tpu.runtime.cluster import InProcFabric

    class Echo(Grain):
        async def ping(self):
            return 1

    fabric = InProcFabric()
    silo = (SiloBuilder().with_fabric(fabric).add_grains(Echo)).build()
    order = []
    fabric.is_dead = lambda a: False  # S1/S2 are stand-in peers
    fabric.deliver_group = lambda dest, msgs: order.append(
        ("group", dest, len(msgs)))
    fabric.deliver = lambda msg: order.append(("single", msg.target_silo))
    req = make_request(target_grain=GrainId.for_grain(GT, 1),
                      interface_name="Echo", method_name="ping",
                      body=((), {}), sending_silo=S2, target_silo=S1)
    resp = make_response(req, 1)
    silo.dispatcher.send_response(req, resp)        # accumulates for S2
    assert silo.message_center.egress.groups
    follow = make_request(target_grain=GrainId.for_grain(GT, 2),
                          interface_name="Echo", method_name="ping",
                          body=((), {}), target_silo=S2)
    silo.message_center.send_message(follow)
    assert order[0][0] == "group" and order[0][1] == S2
    assert order[1][0] == "single"


# ---------------------------------------------------------------------------
# Batched client-side correlation
# ---------------------------------------------------------------------------

class _StubClient(RuntimeClient):
    """RuntimeClient with a recording transmit/deliver surface."""

    def __init__(self):
        super().__init__(response_timeout=5.0)
        self.delivered = []

    @property
    def silo_address(self):
        return S2

    def transmit(self, msg):
        pass

    def deliver(self, msg):
        # the real client deliver contract: responses correlate,
        # everything else dispatches (observers)
        if msg.direction == Direction.RESPONSE:
            self.receive_response(msg)
        else:
            self.delivered.append(msg)


async def test_receive_response_batch_resolves_and_sweeps():
    client = _StubClient()
    loop = asyncio.get_running_loop()
    reqs, futs, resps = [], [], []
    for i in range(6):
        req = make_request(target_grain=GrainId.for_grain(GT, i),
                           interface_name="eg.IEcho", method_name="m",
                           body=((), {}), sending_silo=S2, target_silo=S1)
        fut = loop.create_future()
        client.callbacks[req.id] = _fresh_callback(req, fut, None, None)
        if i % 3 == 2:
            resp = make_error_response(req, ValueError(f"e{i}"))
        else:
            resp = make_response(req, i * 10)
        reqs.append(req)
        futs.append(fut)
        resps.append(resp)
    client.receive_response_batch(resps)
    assert not client.callbacks
    for i, fut in enumerate(futs):
        if i % 3 == 2:
            with pytest.raises(ValueError):
                fut.result()
        else:
            assert fut.result() == i * 10
    # ONE release sweep retired both envelopes of every settled RPC
    assert all(m._pool_free for m in reqs)
    assert all(m._pool_free for m in resps)


async def test_receive_response_batch_rejection_delegates():
    """Rejections keep their exact per-message semantics (here: the
    terminal rejection error) through the batched entry."""
    client = _StubClient()
    loop = asyncio.get_running_loop()
    req = make_request(target_grain=GrainId.for_grain(GT, 1),
                       interface_name="eg.IEcho", method_name="m",
                       body=((), {}), sending_silo=S2, target_silo=S1)
    req.resend_count = 3  # over MAX_RESEND_COUNT: rejection is terminal
    fut = loop.create_future()
    client.callbacks[req.id] = _fresh_callback(req, fut, None, None)
    rej = make_rejection(req, RejectionType.TRANSIENT, "nope")
    ok_req = make_request(target_grain=GrainId.for_grain(GT, 2),
                          interface_name="eg.IEcho", method_name="m",
                          body=((), {}), sending_silo=S2, target_silo=S1)
    ok_fut = loop.create_future()
    client.callbacks[ok_req.id] = _fresh_callback(ok_req, ok_fut, None, None)
    client.receive_response_batch([rej, make_response(ok_req, "ok")])
    from orleans_tpu.core.errors import RejectionError
    with pytest.raises(RejectionError):
        fut.result()
    assert ok_fut.result() == "ok"


async def test_deliver_batch_mixed_runs_preserve_order():
    client = _StubClient()
    loop = asyncio.get_running_loop()
    req = make_request(target_grain=GrainId.for_grain(GT, 1),
                       interface_name="eg.IEcho", method_name="m",
                       body=((), {}), sending_silo=S2, target_silo=S1)
    fut = loop.create_future()
    client.callbacks[req.id] = _fresh_callback(req, fut, None, None)
    notify = make_request(target_grain=GrainId.for_grain(GT, 9),
                          interface_name="Observer", method_name="notify",
                          body=((), {}), direction=Direction.ONE_WAY)
    client.deliver_batch([notify, make_response(req, 5)])
    assert client.delivered == [notify]
    assert fut.result() == 5
    # the per-message lever: batched correlation off, deliver() sees all
    client.batched_egress = False
    req2 = make_request(target_grain=GrainId.for_grain(GT, 3),
                        interface_name="eg.IEcho", method_name="m",
                        body=((), {}), sending_silo=S2, target_silo=S1)
    fut2 = loop.create_future()
    client.callbacks[req2.id] = _fresh_callback(req2, fut2, None, None)
    client.deliver_batch([make_response(req2, 6)])
    assert fut2.result() == 6  # deliver() -> receive_response per message


# ---------------------------------------------------------------------------
# Pool discipline
# ---------------------------------------------------------------------------

def test_recycle_messages_batch_sweep_semantics():
    req = make_request(target_grain=GrainId.for_grain(GT, 1),
                       interface_name="eg.IEcho", method_name="m",
                       body=((1,), {}), sending_silo=S2, target_silo=S1)
    resp = make_response(req, {"big": [1, 2, 3]})
    prev = set_debug_pool(True)
    try:
        g_req, g_resp = pool_generation(req), pool_generation(resp)
        recycle_messages([req, resp])
        assert req._pool_free and resp._pool_free
        assert pool_generation(req) == g_req + 1
        assert pool_generation(resp) == g_resp + 1
        assert req.body is None and resp.body is None
        # idempotent: a second sweep is a no-op (no double generation)
        recycle_messages([req, resp])
        assert pool_generation(req) == g_req + 1
    finally:
        set_debug_pool(prev)


# ---------------------------------------------------------------------------
# End-to-end over real sockets
# ---------------------------------------------------------------------------

def _vector_counter():
    import jax.numpy as jnp

    from orleans_tpu.dispatch import VectorGrain, actor_method

    class CounterVec(VectorGrain):
        STATE = {"count": (jnp.int32, ())}

        @staticmethod
        def initial_state(key_hash):
            return {"count": jnp.int32(0)}

        @actor_method(args={"x": (jnp.int32, ())})
        def bump(state, args):
            return {"count": state["count"] + 1}, state["count"]

    return CounterVec


async def _socket_cluster(vec_cls=None, n_keys: int = 32, **cfg):
    from orleans_tpu.runtime.socket_fabric import GatewayClient, SocketFabric

    class EchoGrain(Grain):
        async def ping(self, x):
            return x

    fabric = SocketFabric()
    b = (SiloBuilder().with_name("eg").with_fabric(fabric)
         .add_grains(EchoGrain).with_config(**cfg))
    if vec_cls is not None:
        from orleans_tpu.dispatch import add_vector_grains
        from orleans_tpu.parallel import make_mesh
        add_vector_grains(b, vec_cls, mesh=make_mesh(1),
                          dense={vec_cls: n_keys})
    silo = b.build()
    await silo.start()
    client = await GatewayClient([silo.silo_address.endpoint]).connect()
    return silo, client, EchoGrain


@pytest.mark.parametrize("egress", [True, False])
async def test_vector_call_batch_results_identical_either_lever(egress):
    CounterVec = _vector_counter()
    silo, client, EchoGrain = await _socket_cluster(
        CounterVec, batched_egress=egress)
    client.batched_egress = egress
    try:
        assert (silo.message_center.egress is not None) == egress
        # vector burst through call_batch: responses resolve from one
        # inbound batch — the exact shape the egress pipeline groups
        outs = await asyncio.gather(*client.call_batch(
            CounterVec, "bump",
            [(k, {"x": np.int32(0)}) for k in range(32)]))
        assert [int(v) for v in outs] == [0] * 32
        outs2 = await asyncio.gather(*client.call_batch(
            CounterVec, "bump",
            [(k, {"x": np.int32(0)}) for k in range(32)]))
        assert [int(v) for v in outs2] == [1] * 32
        # host-tier burst: eager-ish turn completions group the same way
        g = client.get_grain(EchoGrain, "h")
        vals = await asyncio.gather(*(g.ping(i) for i in range(50)))
        assert vals == list(range(50))
    finally:
        await client.close_async()
        await silo.stop()


async def test_recycle_discipline_under_debug_pool_batched_egress():
    """ORLEANS_TPU_DEBUG_POOL=1 across the whole batched response path:
    send_response_batch → egress accumulator → wire template → client
    batch correlation → one freelist sweep. Any shell touched after
    recycle (or recycled twice into service) trips PoolDisciplineError."""
    prev = set_debug_pool(True)
    try:
        CounterVec = _vector_counter()
        silo, client, EchoGrain = await _socket_cluster(CounterVec,
                                                        n_keys=16)
        try:
            g = client.get_grain(EchoGrain, "pool")
            for _ in range(3):
                outs = await asyncio.gather(
                    *(g.ping(i) for i in range(20)),
                    *client.call_batch(
                        CounterVec, "bump",
                        [(k, {"x": np.int32(0)}) for k in range(16)]))
                assert list(outs[:20]) == list(range(20))
        finally:
            await client.close_async()
            await silo.stop()
    finally:
        set_debug_pool(prev)


# ---------------------------------------------------------------------------
# Metrics: stages populated when on, nothing when off
# ---------------------------------------------------------------------------

async def test_egress_stats_populated_and_gauge_registered():
    CounterVec = _vector_counter()
    silo, client, _ = await _socket_cluster(CounterVec,
                                            metrics_enabled=True,
                                            metrics_sample_period=0.05)
    try:
        await asyncio.gather(*client.call_batch(
            CounterVec, "bump",
            [(k, {"x": np.int32(0)}) for k in range(32)]))
        await asyncio.sleep(0.15)  # a sampler tick
        snap = silo.stats.snapshot()
        assert snap["counters"].get(EGRESS_STATS["responses"], 0) > 0
        hists = snap["histograms"]
        for stage in ("build", "dwell", "group"):
            assert hists.get(EGRESS_STATS[stage], {}).get("count", 0) > 0, \
                f"egress stage {stage} never observed"
        # encode is observed fabric-side (shared senders) — present too
        assert hists.get(EGRESS_STATS["encode"], {}).get("count", 0) > 0
        assert hists[EGRESS_STATS["group"]]["mean"] > 1.0, \
            "responses are not grouping (mean flush-group size <= 1)"
        assert "vector.egress_group" in snap["gauges"]
    finally:
        await client.close_async()
        await silo.stop()


async def test_egress_disabled_costs_nothing():
    """metrics_enabled=False: no EGRESS series may materialize — the off
    path pays one None check per site, the ingest-stage discipline."""
    CounterVec = _vector_counter()
    silo, client, _ = await _socket_cluster(CounterVec)
    try:
        await asyncio.gather(*client.call_batch(
            CounterVec, "bump",
            [(k, {"x": np.int32(0)}) for k in range(16)]))
        for name in EGRESS_STATS.values():
            assert name not in silo.stats.histograms
            assert name not in silo.stats.counters
    finally:
        await client.close_async()
        await silo.stop()


# ---------------------------------------------------------------------------
# Tracing parity on the batched path
# ---------------------------------------------------------------------------

async def test_response_leg_span_rides_batched_egress():
    """_stamp_response's wall stamp crosses the batched wire in the
    template's varying request_context field; the client's batched
    correlation records the response-leg network span identically."""
    CounterVec = _vector_counter()
    silo, client, EchoGrain = await _socket_cluster(
        CounterVec, trace_enabled=True, metrics_enabled=True)
    client.enable_tracing(sample_rate=1.0)
    try:
        g = client.get_grain(EchoGrain, "traced")
        assert await asyncio.gather(*(g.ping(i) for i in range(8))) == \
            list(range(8))
        # the batched pipeline actually carried the responses
        assert silo.stats.get(EGRESS_STATS["responses"]) > 0
        spans = client.tracer.snapshot()
        legs = [s for s in spans if s["kind"] == "network"
                and s["attrs"].get("leg") == "response"]
        assert legs, f"no response-leg network span in {spans}"
    finally:
        await client.close_async()
        await silo.stop()
