"""ICI transport tests: all_to_all message exchange semantics on the
8-device CPU mesh (the comm-backend tier of SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np

from orleans_tpu.parallel import make_mesh
from orleans_tpu.parallel.transport import build_exchange


def test_exchange_routes_to_correct_shard():
    mesh = make_mesh(8)
    n = 8
    B, CAP = 16, 4
    ex = build_exchange(mesh, capacity=CAP)
    # shard s sends one message to shard (s+1) % n carrying value 100+s
    dest = np.zeros((n, B), np.int32)
    valid = np.zeros((n, B), bool)
    val = np.zeros((n, B), np.int32)
    for s in range(n):
        dest[s, 0] = (s + 1) % n
        valid[s, 0] = True
        val[s, 0] = 100 + s
    recv, rvalid, drops = ex(jnp.asarray(dest), jnp.asarray(valid),
                             {"v": jnp.asarray(val)})
    recv, rvalid = np.asarray(recv["v"]), np.asarray(rvalid)
    assert int(np.asarray(drops).sum()) == 0
    for s in range(n):
        got = recv[s][rvalid[s]]
        assert got.tolist() == [100 + (s - 1) % n], (s, got)


def test_exchange_fan_in_many_to_one():
    mesh = make_mesh(8)
    n, B, CAP = 8, 8, 16
    ex = build_exchange(mesh, capacity=CAP)
    # every shard sends all 8 messages to shard 3
    dest = np.full((n, B), 3, np.int32)
    valid = np.ones((n, B), bool)
    val = np.arange(n * B, dtype=np.int32).reshape(n, B)
    recv, rvalid, drops = ex(jnp.asarray(dest), jnp.asarray(valid),
                             {"v": jnp.asarray(val)})
    rvalid = np.asarray(rvalid)
    assert int(np.asarray(drops).sum()) == 0
    assert rvalid[3].sum() == n * B
    for s in range(n):
        if s != 3:
            assert rvalid[s].sum() == 0
    got = sorted(np.asarray(recv["v"])[3][rvalid[3]].tolist())
    assert got == sorted(val.reshape(-1).tolist())


def test_exchange_capacity_overflow_drops_and_counts():
    mesh = make_mesh(8)
    n, B, CAP = 8, 8, 2
    ex = build_exchange(mesh, capacity=CAP)
    dest = np.zeros((n, B), np.int32)  # everyone floods shard 0
    valid = np.ones((n, B), bool)
    val = np.ones((n, B), np.int32)
    recv, rvalid, drops = ex(jnp.asarray(dest), jnp.asarray(valid),
                             {"v": jnp.asarray(val)})
    drops = np.asarray(drops)
    rvalid = np.asarray(rvalid)
    # each shard could only send CAP of its B messages
    assert drops.sum() == n * (B - CAP)
    assert rvalid[0].sum() == n * CAP


def test_exchange_multi_field_payload_and_empty_shards():
    mesh = make_mesh(8)
    n, B, CAP = 8, 4, 4
    ex = build_exchange(mesh, capacity=CAP)
    dest = np.zeros((n, B), np.int32)
    valid = np.zeros((n, B), bool)
    a = np.zeros((n, B), np.float32)
    b = np.zeros((n, B, 3), np.int32)
    # only shard 5 sends: two messages to shard 2
    dest[5, :2] = 2
    valid[5, :2] = True
    a[5, :2] = [1.5, 2.5]
    b[5, 0] = [1, 2, 3]
    b[5, 1] = [4, 5, 6]
    recv, rvalid, drops = ex(jnp.asarray(dest), jnp.asarray(valid),
                             {"a": jnp.asarray(a), "b": jnp.asarray(b)})
    rvalid = np.asarray(rvalid)
    assert rvalid[2].sum() == 2
    got_a = sorted(np.asarray(recv["a"])[2][rvalid[2]].tolist())
    assert got_a == [1.5, 2.5]
    got_b = np.asarray(recv["b"])[2][rvalid[2]]
    assert sorted(got_b.sum(axis=1).tolist()) == [6, 15]


def test_dryrun_multichip_8():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = fn(*args)
    jax.block_until_ready(out)
