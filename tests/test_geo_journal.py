"""Geo-replicated journaled grains: confirmed-event notifications cross
CLUSTER boundaries over the multicluster substrate (gossip-discovered
cluster gateways), so a replica in cluster B sees cluster A's confirmed
events without re-reading primary storage; a partition is healed by the
replicas' gap catch-up against the shared primary storage. Reference:
PrimaryBasedLogViewAdaptor.cs:907 (notification tracking) +
LogConsistency/ProtocolGateway.cs (the cross-cluster notification hop)."""

import asyncio

from orleans_tpu.eventsourcing import JournaledGrain, replicated_journal
from orleans_tpu.membership import FileMembershipTable, join_cluster
from orleans_tpu.multicluster import FileGossipChannel, add_multicluster
from orleans_tpu.runtime import GatewayClient, SiloBuilder, SocketFabric
from orleans_tpu.storage import MemoryStorage

FAST = dict(
    membership_probe_period=0.1,
    membership_probe_timeout=0.2,
    membership_missed_probes_limit=2,
    membership_votes_needed=1,
    membership_iam_alive_period=0.5,
    membership_refresh_period=0.2,
    response_timeout=5.0,
)


class CountingStorage:
    """Per-cluster counting facade over the SHARED primary store, so each
    cluster's reads are attributable (the writer's CAS appends legitimately
    read; the replica cluster must not)."""

    def __init__(self, backend: MemoryStorage):
        self._backend = backend
        self.read_count = 0

    async def read(self, grain_type, grain_id):
        self.read_count += 1
        return await self._backend.read(grain_type, grain_id)

    async def write(self, grain_type, grain_id, state, etag):
        return await self._backend.write(grain_type, grain_id, state, etag)

    def __getattr__(self, name):
        return getattr(self._backend, name)


@replicated_journal
class LedgerGrain(JournaledGrain):
    def initial_state(self):
        return {"total": 0, "entries": 0}

    def apply_event(self, state, event):
        return {"total": state["total"] + event["amount"],
                "entries": state["entries"] + 1}

    async def credit(self, amount: int) -> int:
        self.raise_event({"amount": amount})
        await self.confirm_events()
        return self.version

    async def view(self):
        return (self.version, dict(self.state))


async def _start_cluster(cluster_id, channel, storage, tmp_path,
                         n_silos=1):
    """Start one cluster of n silos (each on its own fabric, joined via
    the shared file membership table). Always returns a list."""
    table = FileMembershipTable(str(tmp_path / f"mbr-{cluster_id}.json"))
    silos = []
    for i in range(n_silos):
        b = (SiloBuilder().with_name(f"{cluster_id}-s{i}")
             .with_fabric(SocketFabric())
             .add_grains(LedgerGrain).with_storage("Default", storage)
             .with_config(**FAST))
        add_multicluster(b, cluster_id, [channel], gossip_period=0.1,
                         maintainer_period=0.5)
        silo = b.build()
        join_cluster(silo, table)
        await silo.start()
        silos.append(silo)
    return silos


async def _wait_gossip(a, b, timeout=10.0):
    async def ready():
        while not (a.multicluster.gateways_of("B")
                   and b.multicluster.gateways_of("A")):
            await asyncio.sleep(0.05)
    await asyncio.wait_for(ready(), timeout)


async def _wait_version(client, key, want, timeout=10.0):
    async def poll():
        while True:
            v, state = await client.get_grain(LedgerGrain, key).view()
            if v >= want:
                return v, state
            await asyncio.sleep(0.05)
    return await asyncio.wait_for(poll(), timeout)


async def test_replica_in_remote_cluster_folds_without_storage_read(tmp_path):
    """Cluster A confirms events; cluster B's replica advances by folding
    the cross-cluster notification — its storage read count stays at the
    single activation-time load."""
    channel = FileGossipChannel(str(tmp_path / "gossip.json"))
    primary = MemoryStorage()  # the shared PRIMARY storage
    sa, sb = CountingStorage(primary), CountingStorage(primary)
    (a,) = await _start_cluster("A", channel, sa, tmp_path)
    (b,) = await _start_cluster("B", channel, sb, tmp_path)
    ca = cb = None
    try:
        await _wait_gossip(a, b)
        ca = await GatewayClient([a.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        cb = await GatewayClient([b.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        # activate B's replica (one storage load) BEFORE A writes
        v, state = await cb.get_grain(LedgerGrain, "book").view()
        assert (v, state) == (0, {"total": 0, "entries": 0})
        reads_after_activation = sb.read_count

        # A's replica confirms two batches
        assert await ca.get_grain(LedgerGrain, "book").credit(10) == 1
        assert await ca.get_grain(LedgerGrain, "book").credit(5) == 2

        # B's replica converges via notifications — no further reads
        v, state = await _wait_version(cb, "book", 2)
        assert state == {"total": 15, "entries": 2}
        assert sb.read_count == reads_after_activation, \
            "replica re-read storage instead of folding notifications"
    finally:
        for c in (ca, cb):
            if c is not None:
                await c.close_async()
        await a.stop()
        await b.stop()


async def test_relay_fans_out_to_every_silo_of_the_remote_cluster(tmp_path):
    """Cluster B has TWO silos, each hosting its own @replicated_journal
    replica. One relay delivery from cluster A must fold into BOTH
    (JournalRelayGrain iterates the receiving cluster's alive_list)."""
    channel = FileGossipChannel(str(tmp_path / "gossip.json"))
    storage = MemoryStorage()
    (a,) = await _start_cluster("A", channel, storage, tmp_path)
    b1, b2 = await _start_cluster("B", channel, storage, tmp_path,
                                  n_silos=2)
    ca = None
    try:
        # B's two silos converge into one cluster first
        async def b_converged():
            while len(b1.membership.active) != 2 or \
                    len(b2.membership.active) != 2:
                await asyncio.sleep(0.05)
        await asyncio.wait_for(b_converged(), timeout=15.0)
        await _wait_gossip(a, b1)
        ca = await GatewayClient([a.silo_address.endpoint],
                                 response_timeout=5.0).connect()

        # activate a replica on EACH B silo directly (stateless-worker
        # placement: one per silo)
        for bs in (b1, b2):
            v, _ = await bs.grain_factory.get_grain(
                LedgerGrain, "shared").view()
            assert v == 0

        await ca.get_grain(LedgerGrain, "shared").credit(7)

        async def both_converged():
            while True:
                views = [await bs.grain_factory.get_grain(
                    LedgerGrain, "shared").view() for bs in (b1, b2)]
                if all(v == 1 and st["total"] == 7 for v, st in views):
                    return
                await asyncio.sleep(0.05)
        await asyncio.wait_for(both_converged(), timeout=10.0)
    finally:
        if ca is not None:
            await ca.close_async()
        await a.stop()
        await b1.stop()
        await b2.stop()


async def test_partitioned_cluster_catches_up_on_heal(tmp_path):
    """Notifications lost during a cluster partition leave B's replica
    with a version gap; once notifications resume, the out-of-order
    notification triggers the gap catch-up read of primary storage and B
    reconverges (the reference's notification-loss → catch-up path)."""
    channel = FileGossipChannel(str(tmp_path / "gossip.json"))
    storage = CountingStorage(MemoryStorage())
    (a,) = await _start_cluster("A", channel, storage, tmp_path)
    (b,) = await _start_cluster("B", channel, storage, tmp_path)
    ca = cb = None
    try:
        await _wait_gossip(a, b)
        ca = await GatewayClient([a.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        cb = await GatewayClient([b.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        await cb.get_grain(LedgerGrain, "ledger").view()  # activate B's
        await ca.get_grain(LedgerGrain, "ledger").credit(1)
        await _wait_version(cb, "ledger", 1)

        # partition: A cannot reach B's gateways — geo notifications fail
        real_client_for = a.gsi._client_for

        async def cut(cluster_id):
            raise ConnectionError("partitioned")
        a.gsi._client_for = cut

        await ca.get_grain(LedgerGrain, "ledger").credit(2)  # B misses v2
        await asyncio.sleep(1.0)  # retries exhaust; B still at v1
        v, _ = await cb.get_grain(LedgerGrain, "ledger").view()
        assert v == 1

        # heal, then another confirm: B gets (from=2,new=3) out of order,
        # buffers it, and the gap catch-up reads primary storage
        a.gsi._client_for = real_client_for
        await ca.get_grain(LedgerGrain, "ledger").credit(3)
        v, state = await _wait_version(cb, "ledger", 3, timeout=15.0)
        assert state == {"total": 6, "entries": 3}
    finally:
        for c in (ca, cb):
            if c is not None:
                await c.close_async()
        await a.stop()
        await b.stop()
