"""Multi-cluster admin configuration: operator-injected cluster lists
with lagging-silo stability checks, config gossip convergence, and
removal semantics (GSI entries owned by a removed cluster demote to
Doubtful and re-home). Reference:
/root/reference/src/Orleans.Runtime/Core/ManagementGrain.cs:387-427
(InjectMultiClusterConfiguration) over MultiClusterOracle.cs."""

import asyncio

import pytest

from orleans_tpu.core.ids import GrainId
from orleans_tpu.management import ManagementGrain, add_management
from orleans_tpu.membership import FileMembershipTable, join_cluster
from orleans_tpu.multicluster import (
    FileGossipChannel,
    GsiState,
    add_multicluster,
    global_single_instance,
)
from orleans_tpu.runtime import GatewayClient, Grain, SiloBuilder, SocketFabric
from orleans_tpu.runtime.grain import grain_type_of

FAST = dict(
    membership_probe_period=0.1,
    membership_probe_timeout=0.2,
    membership_missed_probes_limit=2,
    membership_votes_needed=1,
    membership_iam_alive_period=0.5,
    membership_refresh_period=0.2,
    membership_vote_expiration=5.0,
    response_timeout=5.0,
)


@global_single_instance
class ItemGrain(Grain):
    async def put(self, v):
        self._v = v
        return self.runtime_identity

    async def get(self):
        return (getattr(self, "_v", None), self.runtime_identity)


async def _start_cluster(cluster_id, channel, tmp_path, n_silos=1,
                         maintainer_period=0.2):
    fabric = SocketFabric()
    table = FileMembershipTable(str(tmp_path / f"mbr-{cluster_id}.json"))
    silos = []
    for i in range(n_silos):
        b = (SiloBuilder().with_name(f"{cluster_id}-s{i}")
             .with_fabric(fabric).add_grains(ItemGrain)
             .with_config(**FAST))
        add_multicluster(b, cluster_id, [channel], gossip_period=0.1,
                         maintainer_period=maintainer_period)
        add_management(b)
        silo = b.build()
        join_cluster(silo, table)
        await silo.start()
        silos.append(silo)
    return silos


async def _wait(cond, timeout=10.0, msg="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        await asyncio.sleep(0.05)


async def test_inject_configuration_gossips_to_all_clusters(tmp_path):
    """Injection through the ManagementGrain stamps + gossips the config;
    every cluster's oracle converges on it and known_clusters becomes
    conf-governed (a configured-but-silent cluster stays listed)."""
    channel = FileGossipChannel(str(tmp_path / "gossip.json"))
    (a,) = await _start_cluster("A", channel, tmp_path)
    (b,) = await _start_cluster("B", channel, tmp_path)
    ca = None
    try:
        await _wait(lambda: set(a.multicluster.known_clusters())
                    >= {"A", "B"} and a.multicluster.gateways_of("B"),
                    msg="initial gossip")
        ca = await GatewayClient([a.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        mgmt = ca.get_grain(ManagementGrain, 0)
        assert await mgmt.get_multicluster_configuration() is None
        cfg = await mgmt.inject_multicluster_configuration(
            ["A", "B", "C"], comment="add planned cluster C")
        assert cfg["clusters"] == ["A", "B", "C"]
        # conf-governed membership: C listed though it never gossiped
        assert a.multicluster.known_clusters() == ["A", "B", "C"]
        # B learns the config through the channel
        await _wait(lambda: b.multicluster.config_stamp() == cfg["stamp"],
                    msg="config convergence on B")
        assert b.multicluster.known_clusters() == ["A", "B", "C"]
        assert (await mgmt.get_multicluster_configuration())["comment"] \
            == "add planned cluster C"
    finally:
        if ca is not None:
            await ca.close_async()
        await a.stop()
        await b.stop()


async def test_removed_cluster_entries_rehome(tmp_path):
    """Inject, then REMOVE a cluster: the surviving cluster's CACHED
    entries owned by the removed cluster demote to Doubtful and the
    maintainer re-homes the grains locally — calls that used to forward
    now activate in the surviving cluster."""
    channel = FileGossipChannel(str(tmp_path / "gossip.json"))
    (a,) = await _start_cluster("A", channel, tmp_path)
    (b,) = await _start_cluster("B", channel, tmp_path)
    ca = cb = None
    try:
        await _wait(lambda: set(a.multicluster.known_clusters())
                    >= {"A", "B"} and a.multicluster.gateways_of("B")
                    and b.multicluster.gateways_of("A"),
                    msg="initial gossip")
        ca = await GatewayClient([a.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        cb = await GatewayClient([b.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        # A touches first and owns globally; B caches at A
        where = await ca.get_grain(ItemGrain, "it1").put("v1")
        assert where == str(a.silo_address)
        _, served_by = await cb.get_grain(ItemGrain, "it1").get()
        assert served_by == str(a.silo_address)
        gid = GrainId.for_grain(grain_type_of(ItemGrain), "it1")
        state, owner = await b.gsi.status(gid)
        assert state == GsiState.CACHED.value and owner == "A"
        # operator removes cluster A from the network (via B's mgmt)
        mgmt = cb.get_grain(ManagementGrain, 0)
        cfg = await mgmt.inject_multicluster_configuration(
            ["B"], comment="decommission A")
        assert b.multicluster.known_clusters() == ["B"]
        # B's entry re-homes: Doubtful -> re-registered -> OWNED by B

        async def rehomed():
            s, o = await b.gsi.status(gid)
            return s == GsiState.OWNED.value and o == "B"

        deadline = asyncio.get_running_loop().time() + 10
        while not await rehomed():
            assert asyncio.get_running_loop().time() < deadline, \
                "entry never re-homed to B"
            await asyncio.sleep(0.1)
        # calls through B now serve locally (a fresh activation)
        _, served_by = await cb.get_grain(ItemGrain, "it1").get()
        assert served_by == str(b.silo_address)
        assert cfg["clusters"] == ["B"]
    finally:
        for c in (ca, cb):
            if c is not None:
                await c.close_async()
        await a.stop()
        await b.stop()


async def test_inject_refuses_on_lagging_silo(tmp_path):
    """A silo still gossiping an older configuration stamp blocks
    injection (the stabilization precondition); once it converges the
    injection proceeds."""
    channel = FileGossipChannel(str(tmp_path / "gossip.json"))
    s0, s1 = await _start_cluster("A", channel, tmp_path, n_silos=2)
    ca = None
    try:
        await _wait(lambda: len(s0.locator.alive_list) == 2,
                    msg="2-silo membership")
        ca = await GatewayClient([s0.silo_address.endpoint],
                                 response_timeout=5.0).connect()
        mgmt = ca.get_grain(ManagementGrain, 0)
        first = await mgmt.inject_multicluster_configuration(["A", "B"])
        # simulate a lagging silo: force one oracle onto a divergent stamp
        lagger = s1 if s1.multicluster.config_stamp() == first["stamp"] \
            else s0
        lagger.multicluster.data.config = {
            "clusters": ["A"], "stamp": first["stamp"] - 100,
            "comment": "stale"}
        with pytest.raises(Exception, match="not stabilized"):
            await mgmt.inject_multicluster_configuration(["A"])
        # heal: let gossip re-converge the lagger, then inject succeeds
        await _wait(lambda: s0.multicluster.config_stamp()
                    == s1.multicluster.config_stamp(),
                    msg="stamp convergence")
        cfg = await mgmt.inject_multicluster_configuration(
            ["A"], check_for_lagging_silos=True)
        assert cfg["clusters"] == ["A"]
    finally:
        if ca is not None:
            await ca.close_async()
        await s0.stop()
        await s1.stop()
