"""Shared fake OTLP/HTTP collector for the export tests (spans AND
metrics sinks — one implementation, parameterized by path)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

__all__ = ["FakeCollector"]


class FakeCollector:
    """Minimal local OTLP/HTTP collector: records request bodies; can be
    scripted to fail the first N posts (503 by default) to exercise the
    sinks' retry/backoff path."""

    def __init__(self, fail_first: int = 0, fail_status: int = 503,
                 path: str = "/v1/traces"):
        self.bodies: list[dict] = []
        self.path = path
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — http.server API
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                with outer._lock:
                    if outer.fail_first > 0:
                        outer.fail_first -= 1
                        self.send_response(fail_status)
                        self.end_headers()
                        return
                    outer.bodies.append(json.loads(raw))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):  # keep test output clean
                pass

        self.fail_first = fail_first
        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.server.server_port}{self.path}"

    def span_count(self) -> int:
        with self._lock:
            return sum(len(sp)
                       for b in self.bodies
                       for rs in b["resourceSpans"]
                       for ss in rs["scopeSpans"]
                       for sp in [ss["spans"]])

    def metric_names(self) -> set[str]:
        with self._lock:
            return {m["name"]
                    for b in self.bodies
                    for rm in b["resourceMetrics"]
                    for sm in rm["scopeMetrics"]
                    for m in sm["metrics"]}

    def close(self) -> None:
        self.server.shutdown()
        self.server.server_close()
