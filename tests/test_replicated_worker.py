"""Device-tier stateless workers: class replicated over the mesh axis, no
directory entry, round-robin shard assignment, collective read fan-in
(StatelessWorkerPlacement.cs:6 / StatelessWorkerDirector.cs:8 re-designed
for the device tier; SURVEY §2.4)."""

import numpy as np

import jax.numpy as jnp
import pytest

from orleans_tpu.dispatch import (
    VectorGrain,
    VectorRuntime,
    actor_method,
    replicated_worker,
)
from orleans_tpu.parallel import make_mesh


@replicated_worker
class HitCounter(VectorGrain):
    """Stateless-worker aggregate: per-shard local counters, cluster view
    by collective merge."""

    STATE = {"hits": (jnp.int32, ()), "peak": (jnp.int32, ())}
    MERGE = {"hits": "sum", "peak": "max"}

    @staticmethod
    def initial_state(key_hash):
        return {"hits": jnp.int32(0), "peak": jnp.int32(0)}

    @actor_method(args={"amount": (jnp.int32, ())})
    def record(state, args):
        new = {"hits": state["hits"] + 1,
               "peak": jnp.maximum(state["peak"], args["amount"])}
        return new, new["hits"]


def test_replicated_worker_requires_merge_spec():
    with pytest.raises(TypeError, match="MERGE"):
        @replicated_worker
        class Bad(VectorGrain):
            STATE = {"x": (jnp.int32, ())}

    with pytest.raises(TypeError, match="unknown merge"):
        @replicated_worker
        class Bad2(VectorGrain):
            STATE = {"x": (jnp.int32, ())}
            MERGE = {"x": "avg"}


def test_round_robin_spreads_work_and_merge_folds_replicas():
    rt = VectorRuntime(mesh=make_mesh(8))
    host = rt.replicated_host(HitCounter, n_keys=16)
    n = host.n_shards
    assert n == 8

    # 64 calls to ONE key: with an owned table this is one actor's mailbox;
    # as a stateless worker the calls spread over all 8 shards
    keys = np.zeros(64, dtype=np.int64)
    amounts = np.arange(64, dtype=np.int32)
    host.call_batch("record", keys, {"amount": amounts})

    merged = host.read_merged(np.array([0]))
    # sum-merge: every shard counted its local share — cluster total 64
    assert int(merged["hits"][0]) == 64
    # max-merge: the cluster-wide peak is the global max amount
    assert int(merged["peak"][0]) == 63
    # replicas really are independent (each shard saw 8 of the 64 calls)
    per_shard = np.asarray(host.state["hits"][:, 0])
    assert per_shard.tolist() == [8] * 8


def test_many_keys_and_read_only_merge():
    rt = VectorRuntime(mesh=make_mesh(8))
    host = rt.replicated_host(HitCounter, n_keys=32)
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 32, size=400)
    amounts = rng.integers(0, 1000, size=400).astype(np.int32)
    out = host.call_batch("record", keys,
                          {"amount": amounts})
    assert out.shape == (400,)

    merged = host.read_merged(np.arange(32))
    counts = np.bincount(keys, minlength=32)
    assert np.asarray(merged["hits"]).tolist() == counts.tolist()
    for k in range(32):
        want = int(amounts[keys == k].max()) if counts[k] else 0
        assert int(merged["peak"][k]) == want


@replicated_worker
class Quota(VectorGrain):
    """Nonzero initial state + a read-only method: the read-only first
    touch must not burn the fresh flag (donation + activation guards)."""

    STATE = {"left": (jnp.int32, ())}
    MERGE = {"left": "min"}

    @staticmethod
    def initial_state(key_hash):
        return {"left": jnp.int32(100)}

    @actor_method(args={}, read_only=True)
    def peek(state, args):
        return state, state["left"]

    @actor_method(args={"n": (jnp.int32, ())})
    def take(state, args):
        new = {"left": state["left"] - args["n"]}
        return new, new["left"]


def test_read_only_first_touch_keeps_initial_state():
    rt = VectorRuntime(mesh=make_mesh(2))
    host = rt.replicated_host(Quota, n_keys=4)
    # read-only first touch sees initial_state without persisting it
    out = host.call_batch("peek", np.array([1]))
    assert int(out[0]) == 100
    # the first WRITE still runs initial_state (fresh flag intact) — and
    # state stays usable after the read-only tick (donation guard)
    out = host.call_batch("take", np.array([1]), {"n": np.array([30],
                                                                np.int32)})
    assert int(out[0]) == 70


def test_key_range_and_rehost_validation():
    rt = VectorRuntime(mesh=make_mesh(2))
    host = rt.replicated_host(Quota, n_keys=4)
    with pytest.raises(ValueError, match=r"\[0, 4\)"):
        host.call_batch("peek", np.array([-1]))
    with pytest.raises(ValueError, match=r"\[0, 4\)"):
        host.read_merged(np.array([4]))
    with pytest.raises(ValueError, match="already hosted"):
        rt.replicated_host(Quota, n_keys=8)
    with pytest.raises(TypeError, match="args mismatch"):
        host.call_batch("take", np.array([0]),
                        {"wrong": np.array([1], np.int32)})


def test_single_shard_mesh_degenerates_cleanly():
    rt = VectorRuntime(mesh=make_mesh(1))
    host = rt.replicated_host(HitCounter, n_keys=4)
    host.call_batch("record", np.array([1, 1, 2]),
                    {"amount": np.array([5, 9, 3], np.int32)})
    merged = host.read_merged(np.array([1, 2, 3]))
    assert merged["hits"].tolist() == [2, 1, 0]
    assert merged["peak"].tolist() == [9, 3, 0]
