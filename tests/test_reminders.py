"""Reminder service tests (test/TesterInternal/RemindersTest tier):
registration, ticking, persistence across deactivation, ring re-ranging on
silo death, and the table contract on both backends."""

import asyncio
import time

from orleans_tpu.core.ids import GrainId, GrainType
from orleans_tpu.membership import InMemoryMembershipTable, join_cluster
from orleans_tpu.reminders import (
    InMemoryReminderTable,
    ReminderEntry,
    SqliteReminderTable,
    add_reminders,
)
from orleans_tpu.runtime import ClusterClient, Grain, InProcFabric, SiloBuilder
from orleans_tpu.storage import MemoryStorage

TICKS = {}  # (key, reminder name) -> list of tick times


class AlarmGrain(Grain):
    """IRemindable grain: records reminder ticks in a module-global so the
    test can observe ticks even across re-activations."""

    async def arm(self, name, due, period):
        await self.register_reminder(name, due, period)
        return True

    async def disarm(self, name):
        await self.unregister_reminder(name)

    async def lookup(self, name):
        h = await self.get_reminder(name)
        return None if h is None else h.name

    async def receive_reminder(self, name, status):
        TICKS.setdefault((self.primary_key, name), []).append(
            status.current_tick_time)

    async def die(self):
        self.deactivate_on_idle()


def reminder_tables(tmp_path):
    return [InMemoryReminderTable(),
            SqliteReminderTable(str(tmp_path / "rem.sqlite"))]


async def test_reminder_table_contract(tmp_path):
    gid = GrainId.for_grain(GrainType.of("AlarmGrain"), 7)
    for table in reminder_tables(tmp_path):
        assert await table.read_all() == []
        e = ReminderEntry(gid, "AlarmGrain", "wake", 100.0, 60.0)
        tag1 = await table.upsert_row(e)
        row = await table.read_row(gid, "wake")
        assert row.period == 60.0 and row.etag == tag1
        # upsert same key overwrites with a new etag
        e2 = ReminderEntry(gid, "AlarmGrain", "wake", 100.0, 30.0)
        tag2 = await table.upsert_row(e2)
        assert tag2 != tag1
        assert (await table.read_row(gid, "wake")).period == 30.0
        assert len(await table.read_grain_rows(gid)) == 1
        # etag-checked remove: stale etag fails, fresh succeeds
        assert not await table.remove_row(gid, "wake", tag1)
        assert await table.remove_row(gid, "wake", tag2)
        assert await table.read_row(gid, "wake") is None
        await table.delete_table()


async def start_cluster(n, rem_table=None):
    fabric = InProcFabric()
    mbr = InMemoryMembershipTable()
    rem = rem_table or InMemoryReminderTable()
    silos = []
    for i in range(n):
        silo = (SiloBuilder().with_name(f"r{i}").with_fabric(fabric)
                .add_grains(AlarmGrain)
                .with_storage("Default", MemoryStorage())
                .with_config(membership_probe_period=0.1,
                             membership_probe_timeout=0.15,
                             membership_missed_probes_limit=2,
                             membership_refresh_period=0.3,
                             response_timeout=2.0)
                .build())
        join_cluster(silo, mbr)
        add_reminders(silo, rem, refresh_period=0.2)
        await silo.start()
        silos.append(silo)
    client = await ClusterClient(fabric).connect()
    return fabric, rem, silos, client


async def stop_all(silos, client):
    await client.close_async()
    for s in silos:
        if s.status not in ("Stopped", "Dead"):
            await s.stop()


async def wait_ticks(key, name, count, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(TICKS.get((key, name), [])) >= count:
            return TICKS[(key, name)]
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"reminder {name} got {len(TICKS.get((key, name), []))} ticks, "
        f"wanted {count}")


async def test_reminder_fires_periodically():
    TICKS.clear()
    fabric, rem, silos, client = await start_cluster(1)
    try:
        g = client.get_grain(AlarmGrain, 1)
        await g.arm("beat", 0.1, 0.2)
        ticks = await wait_ticks(1, "beat", 3)
        assert ticks == sorted(ticks)
        assert await g.lookup("beat") == "beat"
        await g.disarm("beat")
        n = len(TICKS[(1, "beat")])
        await asyncio.sleep(0.6)
        assert len(TICKS[(1, "beat")]) <= n + 1  # at most one in-flight tick
    finally:
        await stop_all(silos, client)


async def test_reminder_survives_deactivation():
    TICKS.clear()
    fabric, rem, silos, client = await start_cluster(1)
    try:
        g = client.get_grain(AlarmGrain, 2)
        await g.arm("persist", 0.1, 0.25)
        await wait_ticks(2, "persist", 1)
        await g.die()  # deactivate the grain; reminder must keep firing
        before = len(TICKS[(2, "persist")])
        await wait_ticks(2, "persist", before + 2)
    finally:
        await stop_all(silos, client)


async def test_reminder_reranges_to_survivor_on_silo_death():
    TICKS.clear()
    fabric, rem, silos, client = await start_cluster(3)
    try:
        # arm enough reminders that every silo owns at least one
        for k in range(12):
            await client.get_grain(AlarmGrain, 100 + k).arm("spread", 0.1, 0.3)
        for k in range(12):
            await wait_ticks(100 + k, "spread", 1)
        owners = {s.silo_address: len(s.reminders.local) for s in silos}
        assert sum(owners.values()) == 12
        victim = max(silos, key=lambda s: len(s.reminders.local))
        assert len(victim.reminders.local) > 0
        await victim.stop(graceful=False)
        survivors = [s for s in silos if s is not victim]
        # all 12 keep ticking: survivors adopt the victim's ranges
        counts = {k: len(TICKS[(100 + k, "spread")]) for k in range(12)}
        for k in range(12):
            await wait_ticks(100 + k, "spread", counts[k] + 2, timeout=15.0)
        total_local = sum(len(s.reminders.local) for s in survivors)
        assert total_local == 12
    finally:
        await stop_all(silos, client)
