"""Single-silo integration tests — the analog of the reference's
test/DefaultCluster.Tests tier (basic grain calls, turn semantics,
reentrancy, persistence, timers, stateless workers)."""

import asyncio

import pytest

from orleans_tpu.core import GrainCallTimeoutError, GrainOverloadedError
from orleans_tpu.runtime import (
    ClusterClient,
    Grain,
    InProcFabric,
    RequestContext,
    SiloBuilder,
    StatefulGrain,
    always_interleave,
    one_way,
    read_only,
    reentrant,
    stateless_worker,
)

# ---------------------------------------------------------------------------
# Grain zoo (test/TestGrains analog)
# ---------------------------------------------------------------------------


class HelloGrain(Grain):
    async def say_hello(self, greeting: str) -> str:
        return f"You said: '{greeting}', I say: Hello!"


class CounterGrain(Grain):
    def __init__(self):
        self.count = 0
        self.concurrent = 0
        self.max_concurrent = 0

    async def add(self, n: int) -> int:
        self.concurrent += 1
        self.max_concurrent = max(self.max_concurrent, self.concurrent)
        await asyncio.sleep(0.005)
        self.count += n
        self.concurrent -= 1
        return self.count

    @read_only
    async def get(self) -> int:
        self.concurrent += 1
        self.max_concurrent = max(self.max_concurrent, self.concurrent)
        await asyncio.sleep(0.005)
        self.concurrent -= 1
        return self.count

    @read_only
    async def get_max_concurrent(self) -> int:
        return self.max_concurrent


@reentrant
class ReentrantGrain(Grain):
    def __init__(self):
        self.concurrent = 0
        self.max_concurrent = 0

    async def work(self) -> int:
        self.concurrent += 1
        self.max_concurrent = max(self.max_concurrent, self.concurrent)
        await asyncio.sleep(0.01)
        self.concurrent -= 1
        return self.max_concurrent


class PingPongGrain(Grain):
    """A → B → A call cycle: must not deadlock (call-chain reentrancy,
    Dispatcher.cs:346-357)."""

    async def ping(self, other_key, depth: int) -> int:
        if depth == 0:
            return 0
        other = self.get_grain(PingPongGrain, other_key)
        return 1 + await other.ping(self.primary_key, depth - 1)


class PersistentGrain(StatefulGrain):
    async def set_value(self, v) -> None:
        self.state["value"] = v
        await self.write_state()

    async def get_value(self):
        return self.state.get("value")

    async def die(self) -> None:
        self.deactivate_on_idle()


class TimerGrain(Grain):
    def __init__(self):
        self.ticks = 0

    async def start(self) -> None:
        self.register_timer(self._tick, due=0.01, period=0.01)

    async def _tick(self):
        self.ticks += 1

    async def get_ticks(self) -> int:
        return self.ticks


@stateless_worker(max_local=4)
class WorkerGrain(Grain):
    _instances = 0

    def __init__(self):
        WorkerGrain._instances += 1
        self.me = WorkerGrain._instances

    async def which(self) -> int:
        await asyncio.sleep(0.01)
        return self.me


class OneWayGrain(Grain):
    log: list = []

    @one_way
    async def notify(self, v) -> None:
        OneWayGrain.log.append(v)


class ContextGrain(Grain):
    async def read_baggage(self, key):
        return RequestContext.get(key)


class SlowGrain(Grain):
    async def slow(self) -> str:
        await asyncio.sleep(10.0)
        return "done"


class FailingGrain(Grain):
    async def boom(self):
        raise ValueError("kaboom")


ALL_GRAINS = [HelloGrain, CounterGrain, ReentrantGrain, PingPongGrain,
              PersistentGrain, TimerGrain, WorkerGrain, OneWayGrain,
              ContextGrain, SlowGrain, FailingGrain]


async def start_silo(**cfg):
    silo = (SiloBuilder().with_name("s1").add_grains(*ALL_GRAINS)
            .with_config(**cfg).build())
    await silo.start()
    client = await ClusterClient(
        silo.fabric,
        response_timeout=silo.config.response_timeout).connect()
    return silo, client


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------

async def test_hello_world_end_to_end():
    silo, client = await start_silo()
    try:
        hello = client.get_grain(HelloGrain, 0)
        reply = await hello.say_hello("Good morning!")
        assert reply == "You said: 'Good morning!', I say: Hello!"
    finally:
        await client.close_async()
        await silo.stop()


async def test_turns_are_serialized_on_nonreentrant_grain():
    silo, client = await start_silo()
    try:
        g = client.get_grain(CounterGrain, 1)
        results = await asyncio.gather(*(g.add(1) for _ in range(10)))
        assert sorted(results) == list(range(1, 11))  # strictly serial
        assert await g.get_max_concurrent() == 1
    finally:
        await silo.stop()


async def test_read_only_calls_interleave():
    silo, client = await start_silo()
    try:
        g = client.get_grain(CounterGrain, 2)
        await g.add(5)
        await asyncio.gather(*(g.get() for _ in range(8)))
        assert await g.get_max_concurrent() > 1
    finally:
        await silo.stop()


async def test_reentrant_grain_interleaves():
    silo, client = await start_silo()
    try:
        g = client.get_grain(ReentrantGrain, 3)
        results = await asyncio.gather(*(g.work() for _ in range(8)))
        assert max(results) > 1
    finally:
        await silo.stop()


async def test_call_chain_reentrancy_avoids_deadlock():
    silo, client = await start_silo()
    try:
        a = client.get_grain(PingPongGrain, "a")
        # a → b → a → b ... 6 hops; without call-chain reentrancy this
        # deadlocks when the chain re-enters a busy activation.
        assert await asyncio.wait_for(a.ping("b", 6), timeout=5.0) == 6
    finally:
        await silo.stop()


async def test_grain_state_survives_deactivation():
    silo, client = await start_silo()
    try:
        g = client.get_grain(PersistentGrain, 42)
        await g.set_value({"hp": 100})
        await g.die()
        await asyncio.sleep(0.05)  # let deactivation run
        assert silo.catalog.activation_count() == 0
        # next call re-activates and reloads from storage
        assert await g.get_value() == {"hp": 100}
        assert silo.catalog.activation_count() == 1
    finally:
        await silo.stop()


async def test_timer_ticks():
    silo, client = await start_silo()
    try:
        g = client.get_grain(TimerGrain, 1)
        await g.start()
        await asyncio.sleep(0.1)
        assert await g.get_ticks() >= 3
    finally:
        await silo.stop()


async def test_stateless_worker_scales_out():
    silo, client = await start_silo()
    try:
        g = client.get_grain(WorkerGrain, 0)
        await asyncio.gather(*(g.which() for _ in range(16)))
        instances = len(silo.catalog.by_grain.get(g.grain_id, []))
        assert 1 <= instances <= 4
    finally:
        await silo.stop()


async def test_one_way_returns_immediately():
    silo, client = await start_silo()
    try:
        OneWayGrain.log.clear()
        g = client.get_grain(OneWayGrain, 0)
        assert g.notify("x") is None  # no awaitable
        await asyncio.sleep(0.05)
        assert OneWayGrain.log == ["x"]
    finally:
        await silo.stop()


async def test_request_context_propagates():
    silo, client = await start_silo()
    try:
        RequestContext.set("trace-id", "t-123")
        g = client.get_grain(ContextGrain, 0)
        assert await g.read_baggage("trace-id") == "t-123"
        RequestContext.clear()
    finally:
        await silo.stop()


async def test_grain_error_propagates_to_caller():
    silo, client = await start_silo()
    try:
        g = client.get_grain(FailingGrain, 0)
        with pytest.raises(ValueError, match="kaboom"):
            await g.boom()
    finally:
        await silo.stop()


async def test_call_timeout():
    silo, client = await start_silo(response_timeout=0.2)
    try:
        g = client.get_grain(SlowGrain, 0)
        with pytest.raises(GrainCallTimeoutError):
            await g.slow()
    finally:
        await silo.stop(graceful=False)


async def test_overload_rejection():
    silo, client = await start_silo(max_enqueued_requests=5)
    try:
        g = client.get_grain(CounterGrain, 9)
        results = await asyncio.gather(
            *(g.add(1) for _ in range(50)), return_exceptions=True)
        errors = [r for r in results if isinstance(r, Exception)]
        assert errors, "expected overload rejections"
    finally:
        await silo.stop(graceful=False)


async def test_idle_collection():
    silo, client = await start_silo(collection_age=0.05,
                                    collection_quantum=0.05)
    try:
        g = client.get_grain(HelloGrain, 7)
        await g.say_hello("hi")
        assert silo.catalog.activation_count() == 1
        await asyncio.sleep(0.3)
        assert silo.catalog.activation_count() == 0
    finally:
        await silo.stop()


async def test_per_class_collection_age_overrides_silo_default():
    from orleans_tpu.runtime import collection_age

    @collection_age(0.05)
    class ShortLivedGrain(Grain):
        async def ping(self) -> str:
            return "pong"

    # silo default is long; the class override must win
    silo = (SiloBuilder().with_name("s1")
            .add_grains(*ALL_GRAINS, ShortLivedGrain)
            .with_config(collection_age=3600.0, collection_quantum=0.05)
            .build())
    await silo.start()
    client = await ClusterClient(silo.fabric).connect()
    try:
        g = client.get_grain(ShortLivedGrain, 1)
        h = client.get_grain(HelloGrain, 8)
        await asyncio.gather(g.ping(), h.say_hello("hi"))
        assert silo.catalog.activation_count() == 2
        await asyncio.sleep(0.3)
        # ShortLivedGrain collected, HelloGrain (silo default 1h) survives
        assert silo.catalog.activation_count() == 1
    finally:
        await silo.stop()


async def test_stateless_worker_actually_adds_replicas():
    """Regression: all-busy stateless worker must scale out past 1 replica."""
    silo, client = await start_silo()
    try:
        g = client.get_grain(WorkerGrain, 5)
        await asyncio.gather(*(g.which() for _ in range(16)))
        instances = len(silo.catalog.by_grain.get(g.grain_id, []))
        assert instances > 1, "stateless worker never scaled out"
        assert instances <= 4
    finally:
        await silo.stop()


async def test_argument_isolation():
    """Caller mutations after the call must not leak into the callee
    (deep-copy at send, SerializationManager.DeepCopy semantics)."""
    class HoldGrain(Grain):
        async def hold(self, d):
            self.d = d
            return None

        async def peek(self):
            return self.d

    silo, client = await start_silo()
    silo.registry.register(HoldGrain)
    try:
        g = client.get_grain(HoldGrain, 0)
        payload = {"v": 1}
        await g.hold(payload)
        payload["v"] = 999  # caller mutates after call returns
        assert (await g.peek())["v"] == 1
    finally:
        await silo.stop()


# ---------------------------------------------------------------------------
# Hot-lane dispatch semantics (runtime.hotlane — PR 3)
# ---------------------------------------------------------------------------

async def test_hotlane_engages_on_warm_local_calls():
    """A warm, idle, local activation serves ordinary calls through the
    hot lane (DISPATCH_STATS hit counter moves); results and errors are
    identical to the messaging path."""
    silo, client = await start_silo()
    try:
        g = client.get_grain(HelloGrain, 100)
        await g.say_hello("warm")  # cold: creates the activation (fallback)
        h0 = client.hot_hits
        for i in range(10):
            assert await g.say_hello(str(i)) == \
                f"You said: '{i}', I say: Hello!"
        assert client.hot_hits - h0 == 10
        assert silo.stats.gauge("dispatch.hotlane.hits") >= 0  # gauge wired
        # errors flow through unchanged
        f = client.get_grain(FailingGrain, 100)
        with pytest.raises(ValueError, match="kaboom"):
            await f.boom()  # cold
        h1 = client.hot_hits
        with pytest.raises(ValueError, match="kaboom"):
            await f.boom()  # warm: hot lane, same exception surface
        assert client.hot_hits == h1 + 1
    finally:
        await client.close_async()
        await silo.stop()


async def test_hotlane_busy_gate_falls_back_without_reordering():
    """A WARM non-reentrant activation under a concurrent burst: the first
    call runs inline, the rest hit a busy gate, fall back, and enqueue in
    arrival order — strictly serial results, no interleave, no reorder."""
    silo, client = await start_silo()
    try:
        g = client.get_grain(CounterGrain, 77)
        await g.add(0)  # warm (the cold path covered serialization before)
        results = await asyncio.gather(*(g.add(1) for _ in range(10)))
        assert sorted(results) == results, "queued turns reordered"
        assert results == list(range(1, 11))
        assert await g.get_max_concurrent() == 1
        assert client.hot_fallbacks > 0  # the busy gate declined inline runs
    finally:
        await client.close_async()
        await silo.stop()


async def test_hotlane_deferred_start_reverifies_gate():
    """ensure_future(ref.method()) builds the call coroutine now but runs
    it later: the hot lane re-verifies the gate at execution time, so a
    burst of deferred starts on a warm non-reentrant grain still runs
    strictly serially (regression: the gate decision alone would admit
    every one of them against the then-idle activation)."""
    silo, client = await start_silo()
    try:
        g = client.get_grain(CounterGrain, 78)
        await g.add(0)  # warm
        futs = [asyncio.ensure_future(g.add(1)) for _ in range(8)]
        results = await asyncio.gather(*futs)
        assert sorted(results) == list(range(1, 9))
        assert await g.get_max_concurrent() == 1
    finally:
        await client.close_async()
        await silo.stop()


async def test_hotlane_read_only_interleaves_and_counts():
    """Read-only hot calls interleave with running read-only turns (the
    gate's read-only clause holds for pooled markers too)."""
    silo, client = await start_silo()
    try:
        g = client.get_grain(CounterGrain, 79)
        await g.add(5)
        await asyncio.gather(*(g.get() for _ in range(8)))
        assert await g.get_max_concurrent() > 1
    finally:
        await client.close_async()
        await silo.stop()


async def test_hotlane_request_context_forces_fallback_and_propagates():
    """Ambient RequestContext baggage forces the messaging path (headers
    carry it); the callee still observes the baggage."""
    silo, client = await start_silo()
    try:
        g = client.get_grain(ContextGrain, 50)
        await g.read_baggage("k")  # warm
        RequestContext.set("k", "v-1")
        h0, f0 = client.hot_hits, client.hot_fallbacks
        assert await g.read_baggage("k") == "v-1"
        assert client.hot_hits == h0 and client.hot_fallbacks > f0
        RequestContext.clear()
        assert await g.read_baggage("k") is None  # hot again, no leak
        assert client.hot_hits == h0 + 1
    finally:
        await client.close_async()
        await silo.stop()


async def test_hotlane_sampled_tracing_forces_fallback_intact_span():
    """With a sampling tracer installed every call takes the messaging
    path (span tree must stay intact); at sample_rate=0 the hot lane
    re-engages while an ambient trace context still forces fallback."""
    silo = (SiloBuilder().with_name("traced").add_grains(*ALL_GRAINS)
            .with_config(trace_enabled=True, trace_sample_rate=1.0)
            .build())
    await silo.start()
    client = await ClusterClient(silo.fabric).connect()
    client.enable_tracing(1.0)
    try:
        g = client.get_grain(HelloGrain, 60)
        await g.say_hello("warm")
        h0 = client.hot_hits
        await g.say_hello("traced")
        assert client.hot_hits == h0  # fell back: the call rooted a trace
        spans = client.tracer.snapshot()
        assert any(s["kind"] == "client" for s in spans)
        server = [s for s in silo.tracer.snapshot() if s["kind"] == "server"]
        assert server, "sampled call lost its server span"
        # sample_rate=0: nothing can root a trace → hot lane engages
        client.tracer.sample_rate = 0.0
        await g.say_hello("x")
        assert client.hot_hits == h0 + 1
    finally:
        await client.close_async()
        await silo.stop()


async def test_hotlane_disabled_via_config():
    silo, client = await start_silo(hot_lane_enabled=False)
    client.hot_lane_enabled = False
    try:
        g = client.get_grain(HelloGrain, 70)
        await g.say_hello("a")
        h0 = client.hot_hits
        await g.say_hello("b")
        assert client.hot_hits == h0  # every call messages
    finally:
        await client.close_async()
        await silo.stop()


async def test_failing_timer_tick_keeps_timer_alive():
    class FlakyTimerGrain(Grain):
        def __init__(self):
            self.ticks = 0

        async def start(self):
            self.register_timer(self._tick, due=0.01, period=0.01)

        async def _tick(self):
            self.ticks += 1
            if self.ticks == 1:
                raise RuntimeError("flaky first tick")

        async def get_ticks(self):
            return self.ticks

    silo, client = await start_silo()
    silo.registry.register(FlakyTimerGrain)
    try:
        g = client.get_grain(FlakyTimerGrain, 0)
        await g.start()
        await asyncio.sleep(0.1)
        assert await g.get_ticks() >= 3  # survived the failing tick
    finally:
        await silo.stop()
