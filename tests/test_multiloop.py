"""Multi-loop silo ingress (ISSUE 11): sharded pump loops + SPSC
hand-off rings + native vectored pump — per-grain FIFO across 2 ingress
loops over real TCP, QoS (PING/SYSTEM never through rings or flush
accumulators), ingress_loops=1 parity, clean shutdown draining rings,
vectored-pump byte-identity vs the Python fallback, the stateless-worker
hot lane, and the profiler's eager-aware enter() guard."""

import asyncio
import socket

import pytest

import orleans_tpu.core.serialization as ser
import orleans_tpu.runtime.multiloop as ml
from orleans_tpu.config import ConfigurationError, MessagingOptions
from orleans_tpu.core.ids import GrainId, GrainType, SiloAddress
from orleans_tpu.core.message import (Category, Direction, Message,
                                      make_request, make_response)
from orleans_tpu.membership import FileMembershipTable, join_cluster
from orleans_tpu.runtime import (GatewayClient, Grain, SiloBuilder,
                                 SocketFabric)
from orleans_tpu.runtime.grain import stateless_worker
from orleans_tpu.runtime.multiloop import SpscRing
from orleans_tpu.runtime.wire import decode_frames, encode_message

hw = ser._hotwire

FAST = dict(
    membership_probe_period=0.1,
    membership_probe_timeout=0.2,
    membership_missed_probes_limit=2,
    membership_votes_needed=1,
    membership_iam_alive_period=0.5,
    membership_refresh_period=0.2,
    membership_vote_expiration=5.0,
    response_timeout=5.0,
)

GT = GrainType.of("mlt.Echo")
S1 = SiloAddress("10.7.0.1", 1111, 3)
S2 = SiloAddress("10.7.0.2", 2222, 5)


class SeqGrain(Grain):
    def __init__(self):
        super().__init__()
        self.seen = []

    async def add(self, tag, i):
        self.seen.append((tag, i))
        return i

    async def seen_list(self):
        return list(self.seen)


class EchoGrain(Grain):
    async def echo(self, x):
        return x * 2

    async def where(self):
        return self.runtime_identity


# ---------------------------------------------------------------------------
# Vectored pump: byte/semantics identity vs the Python fallback
# ---------------------------------------------------------------------------

def _frame_corpus(n=24):
    msgs = []
    for i in range(n):
        m = make_request(
            target_grain=GrainId.for_grain(GT, i),
            interface_name="mlt.IEcho", method_name=f"m{i % 3}",
            body=((i,), {"k": bytes(i % 11)}), sending_silo=S2,
            target_silo=S1, timeout=None)
        msgs.append(m)
        if i % 5 == 0:
            r = make_response(m, {"r": i})
            r.target_silo = S2
            msgs.append(r)
    return msgs


def _slots_equal(a: Message, b: Message) -> bool:
    for s in Message.__slots__:
        if s in ("received_at", "_pool_free", "_pool_gen", "expires_at"):
            continue
        if getattr(a, s) != getattr(b, s):
            return False
    return True


@pytest.mark.skipif(hw is None or not hasattr(hw, "sock_recv_batch"),
                    reason="native toolchain unavailable")
async def test_sock_recv_batch_identical_to_python_decode():
    """Property: for the SAME byte stream in adversarial chunk splits,
    the one-C-call vectored pump (recv + decode) yields exactly the
    messages the Python ``decode_frames`` path yields, frame for frame —
    including partial-tail resume across reads."""
    msgs = _frame_corpus()
    data = b"".join(encode_message(m) for m in msgs)
    # Python reference decode
    consumed, ref, bounces = decode_frames(bytearray(data))
    assert consumed == len(data) and not bounces

    for splits in ((1,), (7, 64, 3, 1024), (37,)):
        a, b = socket.socketpair()
        b.setblocking(False)
        got, bounces2, tail = [], [], b""
        pos = si = 0

        def drain_ready(tail):
            while True:
                r = hw.sock_recv_batch(b.fileno(), tail, Message, 4096)
                if r is None:
                    return tail, False
                entries, tail, eof, _n = r
                ml.finish_batch_entries(entries, got, bounces2)
                if eof:
                    return tail, True

        while pos < len(data):
            step = splits[si % len(splits)]
            si += 1
            a.sendall(data[pos:pos + step])
            pos += step
            tail, _ = drain_ready(tail)
        a.close()
        eof = False
        while not eof:
            tail, eof = drain_ready(tail)
            if not eof:
                await asyncio.sleep(0.005)
        b.close()
        assert not bounces2
        assert tail == b""
        assert len(got) == len(ref)
        for g, r_ in zip(got, ref):
            assert _slots_equal(g, r_)


@pytest.mark.skipif(hw is None or not hasattr(hw, "sock_writev"),
                    reason="native toolchain unavailable")
async def test_sock_writev_bytes_identical_to_join():
    chunks = [bytes([i]) * (i * 13 + 1) for i in range(40)]
    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(False)
    want = b"".join(chunks)
    sent = 0
    rest = list(chunks)
    out = bytearray()
    while rest:
        try:
            n = hw.sock_writev(a.fileno(), rest)
        except BlockingIOError:
            n = 0
        sent += n
        # consume what was written from the chunk list
        while rest and n >= len(rest[0]):
            n -= len(rest[0])
            rest.pop(0)
        if rest and n:
            rest[0] = rest[0][n:]
        # drain the peer so the kernel buffer frees up
        try:
            out += b.recv(1 << 20)
        except BlockingIOError:
            pass
    b.setblocking(False)
    try:
        while True:
            chunk = b.recv(1 << 20)
            if not chunk:
                break
            out += chunk
    except BlockingIOError:
        pass
    a.close()
    b.close()
    assert bytes(out) == want


@pytest.mark.skipif(hw is None or not hasattr(hw, "sock_recv_batch"),
                    reason="native toolchain unavailable")
async def test_sock_recv_batch_hostile_announcement_raises():
    a, b = socket.socketpair()
    b.setblocking(False)
    a.sendall(b"\xff\xff\xff\xff\xff\xff\xff\xff" + b"x" * 16)
    with pytest.raises(ValueError):
        hw.sock_recv_batch(b.fileno(), b"", Message, 4096)
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# SPSC ring
# ---------------------------------------------------------------------------

async def test_spsc_ring_coalesced_wakeup_and_backlog():
    loop = asyncio.get_running_loop()
    drained = []
    ring = SpscRing(loop, drained.append)
    for i in range(5):
        ring.push((1, None, [i], 0.0, 0, 1), 1)
    assert ring.backlog() == 5
    await asyncio.sleep(0)          # one wakeup drains the whole burst
    assert [it[2][0] for it in drained] == [0, 1, 2, 3, 4]
    assert ring.backlog() == 0
    assert ring.drained_batches == 5


async def test_spsc_ring_drain_now_recovers_unarmed_items():
    """The clean-shutdown drain: items sitting in the ring whose armed
    wakeup never ran (producer thread stopped mid-hand-off) are swept by
    ``drain_now`` so no decoded message is dropped."""
    loop = asyncio.get_running_loop()
    drained = []
    ring = SpscRing(loop, drained.append)
    # simulate a lost wakeup: enqueue without arming
    ring._items.append((1, None, ["x"], 0.0, 0, 1))
    ring.pushed_msgs += 1
    assert not drained
    ring.drain_now()
    assert drained and drained[0][2] == ["x"]
    assert ring.backlog() == 0


# ---------------------------------------------------------------------------
# End-to-end: 2 ingress loops over real TCP
# ---------------------------------------------------------------------------

async def _start_multiloop_silo(name, table=None, *, loops=2, grains=(),
                                **cfg):
    fabric = SocketFabric()
    silo = (SiloBuilder().with_name(name).with_fabric(fabric)
            .add_grains(SeqGrain, EchoGrain, *grains)
            .with_config(**{**FAST, "ingress_loops": loops, **cfg}).build())
    if table is not None:
        join_cluster(silo, table)
    await silo.start()
    return silo


async def test_multiloop_fifo_per_grain_across_two_loops():
    """Two clients (two connections, round-robined onto different
    ingress loops) pipeline ordered bursts at the same grains: each
    sender's per-grain order must survive the shard pump + ring
    hand-off exactly (per-sender-per-target FIFO, the wire's one
    guarantee)."""
    silo = await _start_multiloop_silo("mlfifo")
    c1 = c2 = None
    try:
        ep = silo.silo_address.endpoint
        c1 = await GatewayClient([ep], response_timeout=5.0).connect()
        c2 = await GatewayClient([ep], response_timeout=5.0).connect()
        n, grains = 60, 4

        async def burst(client, tag):
            futs = []
            for i in range(n):
                g = client.get_grain(SeqGrain, i % grains)
                futs.append(asyncio.ensure_future(g.add(tag, i)))
            await asyncio.gather(*futs)

        await asyncio.gather(burst(c1, "a"), burst(c2, "b"))
        # both loops actually pumped
        used = [s for s in silo.ingress_pool.shards if s.frames > 0]
        assert len(used) >= 2, \
            f"connections not spread: {[s.frames for s in silo.ingress_pool.shards]}"
        for k in range(grains):
            seen = await c1.get_grain(SeqGrain, k).seen_list()
            for tag in ("a", "b"):
                seq = [i for t, i in seen if t == tag]
                assert seq == sorted(seq), \
                    f"grain {k} tag {tag} reordered: {seq}"
                assert len(seq) == n // grains
    finally:
        for c in (c1, c2):
            if c is not None:
                await c.close_async()
        await silo.stop()


async def test_multiloop_parity_with_single_loop():
    """ingress_loops=1 (the default) constructs NO pool — today's
    start_server path bit for bit — and the same workload returns the
    same results under both settings."""
    results = {}
    for loops in (1, 2):
        silo = await _start_multiloop_silo(f"mlpar{loops}", loops=loops)
        client = None
        try:
            assert (silo.ingress_pool is None) == (loops == 1)
            client = await GatewayClient(
                [silo.silo_address.endpoint], response_timeout=5.0).connect()
            outs = await asyncio.gather(
                *(client.get_grain(EchoGrain, i).echo(i) for i in range(32)))
            results[loops] = outs
        finally:
            if client is not None:
                await client.close_async()
            await silo.stop()
    assert results[1] == results[2] == [2 * i for i in range(32)]


async def test_multiloop_python_fallback_pump_parity(monkeypatch):
    """With the native vectored pump unavailable (ORLEANS_TPU_NATIVE=0
    form), the shard's Python sock_recv + decode_frames pump delivers
    identical results."""
    monkeypatch.setattr(ml, "_HW_SOCK", False)
    silo = await _start_multiloop_silo("mlpy")
    client = None
    try:
        client = await GatewayClient(
            [silo.silo_address.endpoint], response_timeout=5.0).connect()
        outs = await asyncio.gather(
            *(client.get_grain(EchoGrain, i).echo(i) for i in range(24)))
        assert outs == [2 * i for i in range(24)]
        assert any(s.frames for s in silo.ingress_pool.shards)
    finally:
        if client is not None:
            await client.close_async()
        await silo.stop()


async def test_pipelined_frames_behind_handshake_are_served():
    """A conformant peer may write its handshake AND its first request
    in one burst; the bytes the shard reads behind the handshake seed
    the pump's tail and must be decoded immediately — not parked until
    the peer (which is waiting for the response) sends more."""
    from orleans_tpu.runtime.wire import (decode_message, encode_handshake,
                                          read_frame)
    silo = await _start_multiloop_silo("mlpipe")
    writer = None
    try:
        pseudo = SiloAddress("127.0.0.1", 45999, 1234567)
        req = make_request(
            target_grain=GrainId.for_grain(GrainType.of("EchoGrain"), 5),
            interface_name="EchoGrain", method_name="echo",
            body=((7,), {}), sending_silo=pseudo, timeout=5.0)
        host, port = silo.silo_address.endpoint.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        writer.write(encode_handshake("client", pseudo)
                     + encode_message(req))
        await writer.drain()
        await asyncio.wait_for(read_frame(reader), 5.0)  # handshake reply
        rh, rb = await asyncio.wait_for(read_frame(reader), 5.0)
        resp = decode_message(rh, rb)
        assert resp.direction == Direction.RESPONSE
        assert resp.body == 14  # echo(7) == 7 * 2
    finally:
        if writer is not None:
            writer.close()
        await silo.stop()


async def test_multiloop_qos_ping_system_bypass_rings(tmp_path):
    """PING/SYSTEM traffic (membership probes, control RPCs) must NEVER
    ride the shard rings — it is handed to the main loop per-message,
    ring-free, so probes can't sit behind application drains (a delayed
    probe response gets healthy silos voted dead). Every shard must
    satisfy frames == qos_direct + ring-delivered application count, and
    membership must hold steady while both silos run multi-loop."""
    table = FileMembershipTable(str(tmp_path / "mbr.json"))
    s1 = await _start_multiloop_silo("mlq1", table)
    s2 = await _start_multiloop_silo("mlq2", table)
    client = None
    try:
        async def converged():
            while True:
                views = [set(s.membership.active) for s in (s1, s2)]
                if all(len(v) == 2 for v in views) and views[0] == views[1]:
                    return
                await asyncio.sleep(0.05)
        await asyncio.wait_for(converged(), timeout=10.0)

        client = await GatewayClient(
            [s1.silo_address.endpoint], response_timeout=5.0).connect()
        # application traffic spread across both silos while probes flow
        for _ in range(6):
            await asyncio.gather(
                *(client.get_grain(EchoGrain, i).echo(i) for i in range(24)))
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.5)  # several probe periods under load

        saw_qos = 0
        for silo in (s1, s2):
            for sh in silo.ingress_pool.shards:
                ring_msgs = sh.ring.pushed_msgs
                assert sh.frames == sh.qos_direct + ring_msgs, \
                    (sh.frames, sh.qos_direct, ring_msgs)
                saw_qos += sh.qos_direct
        assert saw_qos > 0, "no PING/SYSTEM traffic crossed the shards"
        # membership stayed converged: no probe starved behind a ring
        assert all(len(s.membership.active) == 2 for s in (s1, s2))
    finally:
        if client is not None:
            await client.close_async()
        await s2.stop()
        await s1.stop()


async def test_system_responses_never_enter_flush_accumulator():
    """The egress half of the QoS split (held over from PR 10, asserted
    here beside the ring half): PING/SYSTEM responses take the
    per-message path — the flush accumulator only ever holds
    APPLICATION responses."""
    silo = await _start_multiloop_silo("mlsys", loops=1)
    try:
        eg = silo.message_center.egress
        assert eg is not None
        req = make_request(
            target_grain=GrainId.for_grain(GT, 1),
            interface_name="mlt.IEcho", method_name="m", body=((), {}),
            category=Category.SYSTEM, sending_silo=S2,
            target_silo=silo.silo_address)
        resp = make_response(req, "pong")
        silo.dispatcher.send_response(req, resp)
        assert not eg.groups, "SYSTEM response parked in the accumulator"
        # APPLICATION responses DO group (the accumulator's purpose)
        areq = make_request(
            target_grain=GrainId.for_grain(GT, 2),
            interface_name="mlt.IEcho", method_name="m", body=((), {}),
            sending_silo=S2, target_silo=silo.silo_address)
        aresp = make_response(areq, "ok")
        silo.dispatcher.send_response(areq, aresp)
        assert eg.groups
        eg.flush()
    finally:
        await silo.stop()


async def test_multiloop_clean_shutdown_drains_and_joins():
    """Stop under load: pump threads join, every ring is drained
    (pushed == drained, backlog 0), and the silo exits cleanly."""
    silo = await _start_multiloop_silo("mlstop")
    client = await GatewayClient(
        [silo.silo_address.endpoint], response_timeout=5.0).connect()
    stop = asyncio.Event()

    async def hammer():
        i = 0
        g = client.get_grain(EchoGrain, 1)
        while not stop.is_set():
            try:
                await g.echo(i)
            except Exception:  # noqa: BLE001 — silo stopping under us
                return
            i += 1

    tasks = [asyncio.ensure_future(hammer()) for _ in range(8)]
    await asyncio.sleep(0.3)
    pool = silo.ingress_pool
    stop.set()
    await silo.stop()
    await client.close_async()
    await asyncio.gather(*tasks, return_exceptions=True)
    assert silo.status == "Stopped"
    for sh in pool.shards:
        assert not sh.is_alive()
        assert sh.ring.backlog() == 0
        assert sh.ring.pushed_msgs == sh.ring.drained_msgs


async def test_ingress_loops_config_validation():
    with pytest.raises(ConfigurationError):
        MessagingOptions(ingress_loops=0).validate()
    with pytest.raises(ConfigurationError):
        MessagingOptions(ingress_loops=2.5).validate()
    MessagingOptions(ingress_loops=4).validate()
    silo = (SiloBuilder().with_name("cfg")
            .with_options(MessagingOptions(ingress_loops=3)).build())
    assert silo.config.ingress_loops == 3


# ---------------------------------------------------------------------------
# Satellite: stateless-worker hot lane
# ---------------------------------------------------------------------------

@stateless_worker(max_local=4)
class Worker(Grain):
    async def work(self, x):
        return x + 1

    async def slow(self, x):
        await asyncio.sleep(0.03)
        return x


async def test_stateless_worker_hot_lane_engages():
    """StatelessWorker grains no longer fall back to messaging: an idle
    replica serves the collapsed inline turn (the ROADMAP carry-over)."""
    silo = SiloBuilder().add_grains(Worker).build()
    await silo.start()
    try:
        rc = silo.runtime_client
        g = silo.grain_factory.get_grain(Worker, 1)
        await g.work(0)  # activate the first replica
        h0, f0 = rc.hot_hits, rc.hot_fallbacks
        for i in range(64):
            assert await g.work(i) == i + 1
        assert rc.hot_hits - h0 == 64
        assert rc.hot_fallbacks - f0 == 0
    finally:
        await silo.stop()


async def test_stateless_worker_busy_set_falls_back_and_scales():
    """All replicas busy → the lane declines and the catalog's
    least-loaded pick + auto-scale stay authoritative (replicas grow
    under a concurrent suspending burst, bounded by the cap)."""
    silo = SiloBuilder().add_grains(Worker).build()
    await silo.start()
    try:
        g = silo.grain_factory.get_grain(Worker, 9)
        outs = await asyncio.gather(*(g.slow(i) for i in range(12)))
        assert sorted(outs) == list(range(12))
        acts = [a for k, v in silo.catalog.by_grain.items()
                for a in v if a.grain_class is Worker]
        assert 1 < len(acts) <= 4  # scaled out, capped at max_local
    finally:
        await silo.stop()


# ---------------------------------------------------------------------------
# Satellite: eager-aware profiler enter()
# ---------------------------------------------------------------------------

async def test_profiler_enter_eager_guard(monkeypatch):
    """The guarded boundary: when the current task is in the
    interpreter's eager-task registry, enter() sets the contextvar (so
    post-suspension steps label correctly) but DEFERS the live-slice
    switch — the creator's slice never bleeds. Without the registry
    (py3.10 reference env) behavior is byte-identical to before."""
    from orleans_tpu.observability import profiling
    from orleans_tpu.observability.profiling import LOOP_CATEGORY, LoopProfiler

    lp = LoopProfiler(window=10.0)

    def run_enter():
        lp._depth = 1          # as inside a wrapped callback
        lp._cur = "pump"       # the creator's live category
        tok = lp.enter("turns", "lbl")
        cat = lp._cur
        LOOP_CATEGORY.reset(tok)
        lp._depth = 0
        return cat

    # non-eager (registry absent -> py3.10 path): live switch happens
    monkeypatch.setattr(profiling, "_EAGER_TASKS", None)
    assert run_enter() == "turns"

    # eager step: current task registered -> live switch deferred, the
    # creator's slice keeps accruing; the contextvar still labels the
    # task's own later steps
    lp2 = LoopProfiler(window=10.0)

    def run_enter2():
        lp2._depth = 1
        lp2._cur = "pump"
        tok = lp2.enter("turns")
        cat, var = lp2._cur, LOOP_CATEGORY.get()
        LOOP_CATEGORY.reset(tok)
        lp2._depth = 0
        return cat, var

    monkeypatch.setattr(profiling, "_EAGER_TASKS",
                        {asyncio.current_task()})
    cat, var = run_enter2()
    assert cat == "pump"      # live slice untouched (no bleed)
    assert var == "turns"     # future steps still labeled
