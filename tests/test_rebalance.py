"""Live activation migration & load-aware rebalancing (orleans_tpu.rebalance):
device-tier hot-shard drains, host-tier cross-silo activation migration
under concurrent traffic (zero lost/duplicated invocations), placement
variants, and invalidation-on-forward."""

import asyncio

import jax.numpy as jnp
import pytest

from orleans_tpu.core.ids import SiloAddress
from orleans_tpu.dispatch import VectorGrain, actor_method, add_vector_grains
from orleans_tpu.observability.stats import REBALANCE_STATS
from orleans_tpu.parallel import make_mesh
from orleans_tpu.placement.strategies import (
    ActivationCountP2CPlacement,
    ActivationCountPlacement,
    PlacementManager,
)
from orleans_tpu.rebalance import add_rebalancer
from orleans_tpu.runtime import ClusterClient, SiloBuilder, StatefulGrain
from orleans_tpu.testing import TestClusterBuilder


class CounterVec(VectorGrain):
    STATE = {"count": (jnp.int32, ())}

    @staticmethod
    def initial_state(key_hash):
        return {"count": jnp.int32(0)}

    @actor_method(args={"x": (jnp.int32, ())})
    def bump(state, args):
        new = {"count": state["count"] + args["x"]}
        return new, new["count"]


class HotGrain(StatefulGrain):
    """Host-tier counter; placement pinned in tests via a custom director."""

    __orleans_placement__ = "pin_first"

    async def incr(self) -> int:
        self.state["n"] = self.state.get("n", 0) + 1
        await self.write_state()
        return self.state["n"]

    async def where(self) -> str:
        return str(self.runtime.silo_address)


class PinFirstDirector:
    """Everything lands on one silo — the skew generator."""

    def __init__(self, pinned: SiloAddress):
        self.pinned = pinned

    def place(self, grain_id, requester, silos):
        return self.pinned if self.pinned in silos else silos[0]


def _pin_placement(cluster, pinned) -> None:
    for s in cluster.silos:
        s.locator.placement.directors["pin_first"] = PinFirstDirector(pinned)


# ----------------------------------------------------------------------
# Device tier: hot-shard telemetry + live row migration
# ----------------------------------------------------------------------
async def test_device_hot_shard_drains_to_cool_shards():
    """Hashed keys engineered onto one shard; after a rebalance round the
    hot shard's row count drops and every key's state row survives."""
    b = SiloBuilder().with_name("dev-rebalance").with_config(
        rebalance_budget=16, rebalance_imbalance_ratio=1.1)
    add_vector_grains(b, CounterVec, mesh=make_mesh(8),
                      capacity_per_shard=64)
    add_rebalancer(b)  # period 0: manual rounds
    silo = b.build()
    await silo.start()
    silo.vector.enable_load_tracking()  # manual rounds: opt in explicitly
    client = await ClusterClient(silo.fabric).connect()
    try:
        n_keys, n_shards = 12, 8
        keys = [k * n_shards for k in range(n_keys)]  # all hash to shard 0
        for rep in range(3):
            out = await asyncio.gather(*(
                client.get_grain(CounterVec, k).bump(x=1) for k in keys))
            assert [int(v) for v in out] == [rep + 1] * n_keys
        tbl = silo.vector.table(CounterVec)
        assert all(tbl.key_to_slot[k][0] == 0 for k in keys)
        assert int(tbl.shard_hits()[0]) == 3 * n_keys  # on-device counters
        outcome = await silo.rebalancer.run_round()
        assert outcome["rows_moved"] > 0
        shards_after = {tbl.key_to_slot[k][0] for k in keys}
        assert len(shards_after) > 1, "hot shard did not drain"
        on_hot = sum(1 for k in keys if tbl.key_to_slot[k][0] == 0)
        assert on_hot < n_keys
        # state rows carried exactly: counts continue from 3
        out = await asyncio.gather(*(
            client.get_grain(CounterVec, k).bump(x=1) for k in keys))
        assert [int(v) for v in out] == [4] * n_keys
    finally:
        await client.close_async()
        await silo.stop()


async def test_device_move_fences_pending_invocations():
    """A key with a queued invocation must not move mid-flight (the queued
    _Pending caches its (shard, slot))."""
    b = SiloBuilder().with_name("dev-fence").with_config(
        rebalance_budget=16, rebalance_imbalance_ratio=1.1)
    add_vector_grains(b, CounterVec, mesh=make_mesh(8),
                      capacity_per_shard=64)
    add_rebalancer(b)
    silo = b.build()
    await silo.start()
    silo.vector.enable_load_tracking()
    client = await ClusterClient(silo.fabric).connect()
    try:
        rt = silo.vector
        keys = [k * 8 for k in range(10)]
        await asyncio.gather(*(
            client.get_grain(CounterVec, k).bump(x=1) for k in keys))
        tbl = rt.table(CounterVec)
        # queue an invocation for keys[0] but do NOT let the tick run yet
        fut = rt.call(CounterVec, keys[0], "bump", x=jnp.int32(5))
        assert keys[0] in rt.pending_key_hashes(CounterVec)
        loc_before = tbl.key_to_slot[keys[0]]
        await silo.rebalancer.run_round()
        assert tbl.key_to_slot[keys[0]] == loc_before, "fenced key moved"
        assert int(await fut) == 6  # the queued call still lands correctly
    finally:
        await client.close_async()
        await silo.stop()


# ----------------------------------------------------------------------
# Host tier: the two-silo skewed-workload acceptance scenario
# ----------------------------------------------------------------------
async def test_two_silo_skewed_workload_rebalances_live():
    """Skew every HotGrain onto silo A, drive traffic concurrently with
    the rebalancer loop: at least one migration round runs, silo A's
    activation count decreases, silo B's increases, and NO invocation is
    lost or duplicated (every grain's counter stays gap-free and
    monotonic through its migration)."""
    n_grains, n_rounds = 16, 12
    cluster = (TestClusterBuilder(2).add_grains(HotGrain)
               .with_rebalancer(period=0.15, budget=6, imbalance_ratio=1.1)
               .build())
    async with cluster:
        silo_a, silo_b = cluster.silos
        _pin_placement(cluster, silo_a.silo_address)
        grains = [cluster.grain(HotGrain, f"hot-{i}") for i in range(n_grains)]
        # settle all activations on A
        first = await asyncio.gather(*(g.incr() for g in grains))
        assert first == [1] * n_grains
        count_a_before = silo_a.catalog.activation_count()
        assert count_a_before >= n_grains
        assert silo_b.catalog.activation_count() == 0

        # concurrent traffic while migration rounds run underneath
        for r in range(2, n_rounds + 2):
            out = await asyncio.gather(*(g.incr() for g in grains))
            assert out == [r] * n_grains, f"lost/duplicated call at round {r}"
            await asyncio.sleep(0.05)

        await cluster.wait_until(
            lambda: silo_b.catalog.activation_count() > 0
            and silo_a.catalog.activation_count() < count_a_before,
            timeout=10.0, msg="a migration round to drain silo A")

        # traffic after the move still lands exactly-once
        out = await asyncio.gather(*(g.incr() for g in grains))
        assert out == [n_rounds + 2] * n_grains
        hosts = await asyncio.gather(*(g.where() for g in grains))
        assert str(silo_b.silo_address) in hosts, "no grain serving from B"

        # migration counters are visible in observability.stats
        assert silo_a.stats.get(REBALANCE_STATS["migrated"]) > 0
        assert silo_a.stats.get("catalog.activations.migrated_out") > 0
        assert silo_b.stats.get("catalog.activations.migrated_in") > 0
        assert silo_a.stats.gauge(REBALANCE_STATS["last_imbalance"]) > 0


async def test_migration_mid_flight_messages_redispatch():
    """Messages that race a migration (arrive during the fence) park at
    the source and re-address to the destination — none lost, none run
    twice."""
    cluster = (TestClusterBuilder(2).add_grains(HotGrain)
               .with_rebalancer(period=0.0)  # manual: we drive the executor
               .build())
    async with cluster:
        silo_a, silo_b = cluster.silos
        _pin_placement(cluster, silo_a.silo_address)
        g = cluster.grain(HotGrain, "racer")
        assert await g.incr() == 1
        act = silo_a.catalog.by_grain[g.grain_id][0]
        # start the migration and race a burst of increments against it
        mig = asyncio.ensure_future(
            silo_a.rebalancer.executor.migrate_activation(
                act, silo_b.silo_address))
        burst = [asyncio.ensure_future(g.incr()) for _ in range(8)]
        assert await mig is True
        vals = await asyncio.gather(*burst)
        assert sorted(vals) == list(range(2, 10)), vals
        assert silo_b.catalog.by_grain.get(g.grain_id), "not serving on B"
        assert not silo_a.catalog.by_grain.get(g.grain_id)
        assert await g.incr() == 10  # state carried exactly


async def test_hotlane_migration_fence_falls_back_cleanly():
    """Hot-lane dispatch across a live migration: calls before the fence
    ride the hot lane, calls during the fence fall back to the messaging
    path (parked + re-addressed, the fence contract), and calls after the
    migration hot-lane again on the destination via the client's
    re-resolved locality hint — no lost or duplicated increments."""
    cluster = (TestClusterBuilder(2).add_grains(HotGrain)
               .with_rebalancer(period=0.0).build())
    async with cluster:
        silo_a, silo_b = cluster.silos
        _pin_placement(cluster, silo_a.silo_address)
        client = cluster.client
        g = cluster.grain(HotGrain, "hot-mover")
        assert await g.incr() == 1      # cold: creates on A
        h0 = client.hot_hits
        assert await g.incr() == 2      # warm: hot lane on A
        assert client.hot_hits == h0 + 1
        act = silo_a.catalog.by_grain[g.grain_id][0]
        mig = asyncio.ensure_future(
            silo_a.rebalancer.executor.migrate_activation(
                act, silo_b.silo_address))
        # deferred burst racing the fence: every call must either run
        # before the fence or fall back and re-address — never inline on
        # the fenced source
        burst = [asyncio.ensure_future(g.incr()) for _ in range(6)]
        assert await mig is True
        vals = await asyncio.gather(*burst)
        assert sorted(vals) == list(range(3, 9)), vals
        assert silo_b.catalog.by_grain.get(g.grain_id)
        # post-migration: the locality hint re-resolves to B and the hot
        # lane re-engages there with the migrated state
        h1 = client.hot_hits
        assert await g.incr() == 9
        assert await g.incr() == 10
        assert client.hot_hits > h1, "hot lane never re-engaged on B"
        assert await g.where() == str(silo_b.silo_address)


async def test_hotlane_locality_hint_survives_silo_kill():
    """A killed (non-graceful) silo keeps its catalog populated — the
    client's hot-lane locality hint must treat a non-Running silo as
    stale, re-resolve once the grain reactivates on a survivor, and not
    pin the dead silo object via the cache."""
    cluster = (TestClusterBuilder(2).add_grains(HotGrain)
               .with_rebalancer(period=0.0).build())
    async with cluster:
        silo_a, silo_b = cluster.silos
        _pin_placement(cluster, silo_a.silo_address)
        client = cluster.client
        g = cluster.grain(HotGrain, "phoenix")
        assert await g.incr() == 1   # cold → activates on A
        assert await g.incr() == 2   # warm → hot lane, hint caches A
        assert client._hot_silo_cache.get(g.grain_id) == silo_a.silo_address
        await cluster.kill_silo(silo_a)
        _pin_placement(cluster, silo_b.silo_address)
        # reactivates on B from storage (last persisted n=2); the stale
        # hint must not disable the lane
        assert await asyncio.wait_for(g.incr(), 10) == 3
        h0 = client.hot_hits
        assert await g.incr() == 4
        assert client.hot_hits > h0, "lane never re-engaged after kill"
        assert client._hot_silo_cache.get(g.grain_id) == silo_b.silo_address


async def test_migration_rolls_back_when_destination_refuses():
    """Transfer failure (class unknown on the destination) leaves the
    source activation serving with its registration intact."""
    cluster = (TestClusterBuilder(2).add_grains(HotGrain)
               .with_rebalancer(period=0.0).build())
    async with cluster:
        silo_a, silo_b = cluster.silos
        _pin_placement(cluster, silo_a.silo_address)
        g = cluster.grain(HotGrain, "stayer")
        assert await g.incr() == 1
        act = silo_a.catalog.by_grain[g.grain_id][0]
        # sabotage the destination: it cannot resolve the class
        silo_b.registry._classes.pop("HotGrain")
        ok = await silo_a.rebalancer.executor.migrate_activation(
            act, silo_b.silo_address)
        assert ok is False
        assert silo_a.stats.get(REBALANCE_STATS["rolled_back"]) + \
            silo_a.stats.get(REBALANCE_STATS["refused"]) > 0
        from orleans_tpu.runtime.activation import ActivationState
        assert act.state == ActivationState.VALID
        assert await g.incr() == 2  # still serving locally, no state loss


# ----------------------------------------------------------------------
# Satellites: placement variants + invalidation-on-forward
# ----------------------------------------------------------------------
def test_activation_count_placement_full_scan_and_p2c():
    silos = [SiloAddress(f"s{i}", 1000 + i, 1) for i in range(5)]
    loads = {s: i * 10 for i, s in enumerate(silos)}
    full = ActivationCountPlacement(lambda s: loads[s])
    # full scan: always the global minimum
    for _ in range(10):
        assert full.place(None, silos[3], silos) == silos[0]
    p2c = ActivationCountP2CPlacement(lambda s: loads[s])
    picks = {p2c.place(None, silos[4], silos) for _ in range(50)}
    # p2c: least-loaded of the sampled pair (+requester) — never the
    # requester (heaviest) unless sampled alone, never worse than sampled
    assert silos[4] not in picks
    assert silos[0] in picks  # min is sampled eventually


def test_placement_manager_exposes_p2c_by_name():
    mgr = PlacementManager(lambda s: 0)
    assert isinstance(mgr.director_by_name("activation_count"),
                      ActivationCountPlacement)
    assert isinstance(mgr.director_by_name("activation_count_p2c"),
                      ActivationCountP2CPlacement)
    # the p2c director is not the full-scan one
    assert type(mgr.director_by_name("activation_count")) is \
        ActivationCountPlacement


async def test_forward_notifies_sender_cache_invalidation():
    """After a migration, a peer whose LRU cache still names the old host
    gets its entry dropped by the forwarding silo (invalidation-on-forward
    now heals OTHER silos, not just the forwarder)."""
    cluster = (TestClusterBuilder(3).add_grains(HotGrain)
               .with_rebalancer(period=0.0).build())
    async with cluster:
        silo_a, silo_b, silo_c = cluster.silos
        _pin_placement(cluster, silo_a.silo_address)
        g = cluster.grain(HotGrain, "cached")
        assert await g.incr() == 1
        gid = g.grain_id
        act = silo_a.catalog.by_grain[gid][0]
        # plant a warm cache entry on C naming A (as a prior call would)
        silo_c.locator.cache.put(gid, silo_a.silo_address)
        ok = await silo_a.rebalancer.executor.migrate_activation(
            act, silo_b.silo_address)
        assert ok is True
        # C sends with its stale cache → lands on A → A forwards to B and
        # notifies C; the call must still succeed (exactly once)
        ref = silo_c.grain_factory.get_grain(HotGrain, "cached")
        assert await ref.incr() == 2
        await cluster.wait_until(
            lambda: silo_c.locator.cache.get(gid) != silo_a.silo_address,
            timeout=5.0, msg="stale cache entry on C to be invalidated")


async def test_rebalance_round_is_noop_when_balanced():
    cluster = (TestClusterBuilder(2).add_grains(HotGrain)
               .with_rebalancer(period=0.0).build())
    async with cluster:
        silo_a, _ = cluster.silos
        outcome = await silo_a.rebalancer.run_round()
        assert outcome["planned"] == 0
        assert silo_a.stats.get(REBALANCE_STATS["rounds"]) == 1
        assert silo_a.stats.gauge(REBALANCE_STATS["last_moved"]) == 0


@pytest.mark.slow
async def test_three_silo_convergence_soak():
    """Multi-round convergence: a heavily skewed 3-silo cluster converges
    to within the imbalance ratio over several rebalance rounds, without
    thrashing activations back and forth (>5s: marked slow)."""
    n_grains = 30
    cluster = (TestClusterBuilder(3).add_grains(HotGrain)
               .with_rebalancer(period=0.2, budget=5, imbalance_ratio=1.2)
               .build())
    async with cluster:
        silo_a = cluster.silos[0]
        _pin_placement(cluster, silo_a.silo_address)
        grains = [cluster.grain(HotGrain, f"soak-{i}")
                  for i in range(n_grains)]
        assert await asyncio.gather(*(g.incr() for g in grains)) \
            == [1] * n_grains

        def converged() -> bool:
            counts = [s.catalog.activation_count() for s in cluster.silos]
            live = [c for c in counts]
            mean = sum(live) / len(live)
            return mean > 0 and max(live) <= 1.3 * mean

        await cluster.wait_until(converged, timeout=20.0,
                                 msg="cluster load convergence")
        # steady traffic through the whole soak stayed exactly-once
        out = await asyncio.gather(*(g.incr() for g in grains))
        assert out == [2] * n_grains
        total_moves = sum(s.stats.get(REBALANCE_STATS["migrated"])
                          for s in cluster.silos)
        assert total_moves >= n_grains // 3  # real redistribution happened
        assert total_moves <= n_grains * 3   # and no migration thrash


async def test_device_rebalance_string_keys_63bit_hashes():
    """String keys ride the full 63-bit uniform hash; the plan pack must
    carry them losslessly (bit 62 is set for ~half of them — an int32
    split would mangle the key and silently skip the move)."""
    from orleans_tpu.core.ids import GrainId, GrainType

    gt = GrainType.of("CounterVec")
    names, i = [], 0
    while len(names) < 10:
        key = f"user-{i}"
        i += 1
        if GrainId.for_grain(gt, key).uniform_hash % 8 == 0:
            names.append(key)
    hashes = [GrainId.for_grain(gt, k).uniform_hash for k in names]
    assert any(h >> 62 for h in hashes), "want at least one bit-62 hash"

    b = SiloBuilder().with_name("dev-strkeys").with_config(
        rebalance_budget=16, rebalance_imbalance_ratio=1.1)
    add_vector_grains(b, CounterVec, mesh=make_mesh(8),
                      capacity_per_shard=64)
    add_rebalancer(b)
    silo = b.build()
    await silo.start()
    silo.vector.enable_load_tracking()
    client = await ClusterClient(silo.fabric).connect()
    try:
        for rep in range(2):
            out = await asyncio.gather(*(
                client.get_grain(CounterVec, k).bump(x=1) for k in names))
            assert [int(v) for v in out] == [rep + 1] * len(names)
        tbl = silo.vector.table(CounterVec)
        assert all(tbl.key_to_slot[h][0] == 0 for h in hashes)
        outcome = await silo.rebalancer.run_round()
        assert outcome["rows_moved"] > 0, "63-bit keys were not moved"
        assert len({tbl.key_to_slot[h][0] for h in hashes}) > 1
        # the broadcast heat consumer surfaced a cluster gauge this round
        assert silo.stats.gauge(
            REBALANCE_STATS["device_hot_ratio"]) >= 1.0
        out = await asyncio.gather(*(
            client.get_grain(CounterVec, k).bump(x=1) for k in names))
        assert [int(v) for v in out] == [3] * len(names)
    finally:
        await client.close_async()
        await silo.stop()


# ----------------------------------------------------------------------
# Host tier: ledger-driven hot-actor candidates (ISSUE 17 satellite)
# ----------------------------------------------------------------------
class SplitDirector:
    """Keys prefixed 'a' land on silo A, everything else on silo B —
    the count-balanced skew generator: counts say balanced, the cost
    ledger says silo A hosts the burner."""

    def __init__(self, a: SiloAddress, b: SiloAddress):
        self.a, self.b = a, b

    def place(self, grain_id, requester, silos):
        want = self.a if str(grain_id.key).startswith("a") else self.b
        return want if want in silos else silos[0]


async def test_ledger_hot_actor_gets_move_counts_never_planned():
    """A silo whose activation COUNTS are balanced but whose cost ledger
    names a hot local grain: the count-based pass plans nothing, and
    with ``rebalance_use_ledger=True`` the ledger pass plans a move for
    exactly the named burner (a migration it previously never got)."""
    from orleans_tpu.rebalance.planner import RebalancePlanner

    cluster = (TestClusterBuilder(2).add_grains(HotGrain)
               .with_config(ledger_enabled=True, ledger_top_k=16)
               .build())
    async with cluster:
        silo_a, silo_b = cluster.silos
        for s in cluster.silos:
            s.locator.placement.directors["pin_first"] = \
                SplitDirector(silo_a.silo_address, silo_b.silo_address)
        grains = [cluster.grain(HotGrain, f"{side}-{i}")
                  for side in ("a", "b") for i in range(4)]
        assert await asyncio.gather(*(g.incr() for g in grains)) == [1] * 8
        assert silo_a.catalog.by_grain  # activations settled per director
        # level the COUNTS exactly (management/system activations skew
        # them): filler grains onto whichever silo runs lighter
        for i in range(64):
            ca = silo_a.catalog.activation_count()
            cb = silo_b.catalog.activation_count()
            if ca == cb:
                break
            side = "a" if ca < cb else "b"
            await cluster.grain(HotGrain, f"{side}-fill-{i}").incr()
        assert silo_a.catalog.activation_count() == \
            silo_b.catalog.activation_count()
        for s in cluster.silos:   # refresh the broadcast load view NOW
            s.load_publisher._publish()
        await asyncio.sleep(0)    # let the load_report turns land

        # the real turn charges are microseconds; overlay a skewed window
        # through the public charge verb: one burner, seven background keys
        led = silo_a.ledger
        led.charge_turn("IHot", "incr", 10.0, key="HotGrain/a-0")
        for i in range(1, 4):
            led.charge_turn("IHot", "incr", 0.05, key=f"HotGrain/a-{i}")

        # counts balanced → the count-based pass plans nothing, and with
        # the lever OFF (the default) the burner never gets a move
        silo_a.config.rebalance_use_ledger = False
        plan = RebalancePlanner(silo_a, budget=4, imbalance_ratio=1.5).plan()
        assert not plan.activation_moves

        silo_a.config.rebalance_use_ledger = True
        plan = RebalancePlanner(silo_a, budget=4, imbalance_ratio=1.5).plan()
        moved = [(m.act.grain_class.__name__, m.act.grain_id.key, m.dest)
                 for m in plan.activation_moves]
        assert moved == [("HotGrain", "a-0", silo_b.silo_address)]
