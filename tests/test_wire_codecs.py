"""External-serializer plug-in seam: user types routed through custom
wire codecs (the Orleans.Serialization.Bond/Protobuf registration slot,
SerializationManager.cs:173-201). One registry covers both builds: the
pickle path (reducer_override) and the native hotwire build's per-value
escape hook."""

import struct

import pytest

from orleans_tpu.core import serialization as ser
from orleans_tpu.core.serialization import (
    deserialize,
    register_wire_codec,
    serialize,
    serialize_portable,
    unregister_wire_codec,
)
from orleans_tpu.runtime import ClusterClient, Grain, SiloBuilder


class Vec2:
    """A user type with a compact custom encoding (8 bytes, no pickle)."""

    def __init__(self, x: float, y: float):
        self.x, self.y = x, y

    def __eq__(self, other):
        return isinstance(other, Vec2) and (self.x, self.y) == \
            (other.x, other.y)

    def __repr__(self):
        return f"Vec2({self.x}, {self.y})"


def _enc(v: Vec2) -> bytes:
    return struct.pack("<ff", v.x, v.y)


def _dec(b: bytes) -> Vec2:
    return Vec2(*struct.unpack("<ff", b))


@pytest.fixture
def vec2_codec():
    register_wire_codec("vec2", Vec2, _enc, _dec)
    try:
        yield
    finally:
        unregister_wire_codec("vec2")


def test_roundtrip_through_custom_codec(vec2_codec, monkeypatch):
    payload = {"pos": Vec2(1.5, -2.0), "tag": "ok",
               "nested": [Vec2(0.25, 0.5)]}
    for native in (True, False):
        if not native:
            monkeypatch.setattr(ser, "_hotwire", None)
        out = deserialize(serialize(payload))
        assert out == payload
    # durable blobs take the seam too
    assert deserialize(serialize_portable(Vec2(3.0, 4.0))) == Vec2(3.0, 4.0)


def test_custom_bytes_actually_used(vec2_codec):
    blob = serialize_portable(Vec2(9.0, 8.0))
    assert struct.pack("<ff", 9.0, 8.0) in blob   # the codec's bytes
    assert b"Vec2" not in blob                    # not pickled by class


def test_unregistered_decoder_fails_loudly(vec2_codec):
    blob = serialize(Vec2(1.0, 2.0))
    unregister_wire_codec("vec2")
    try:
        with pytest.raises(Exception, match="vec2.*not.*registered"):
            deserialize(blob)
    finally:
        register_wire_codec("vec2", Vec2, _enc, _dec)


def test_registration_invariants(vec2_codec):
    class Other:
        pass
    with pytest.raises(ValueError, match="already registered"):
        register_wire_codec("vec2", Other, _enc, _dec)
    # one codec per type: a second NAME for Vec2 is rejected, so an
    # unregister of either name can never silently disable the other
    with pytest.raises(ValueError, match="one codec per type"):
        register_wire_codec("vec2-alt", Vec2, _enc, _dec)
    # re-registering the SAME pair is fine (idempotent deploy scripts)
    register_wire_codec("vec2", Vec2, _enc, _dec)
    # builtin fast-path types can never route through a codec — loud error
    # instead of a silently-ignored registration
    with pytest.raises(ValueError, match="builtin"):
        register_wire_codec("mylist", list, _enc, _dec)


class SubVec(Vec2):
    """Module-level so pickle can reference it by name."""


def test_exact_type_match_only(vec2_codec):
    blob = serialize_portable(SubVec(1.0, 1.0))
    # subclass did NOT route through the codec (falls to pickle), so the
    # restricted unpickler rejects the unregistered module instead of
    # silently truncating the subclass to a Vec2
    with pytest.raises(Exception, match="allowlist|not in"):
        deserialize(blob)


class Holder(Grain):
    async def stash(self, v):
        self._v = v
        return v

    async def nudge(self):
        return Vec2(self._v.x + 1, self._v.y + 1)


async def test_grain_call_carries_custom_coded_type(vec2_codec):
    """The seam holds on the full RPC path: args and results carrying a
    registered type cross the wire through the custom codec."""
    silo = SiloBuilder().add_grains(Holder).build()
    await silo.start()
    client = await ClusterClient(silo.fabric).connect()
    try:
        g = client.get_grain(Holder, 1)
        assert await g.stash(Vec2(2.0, 3.0)) == Vec2(2.0, 3.0)
        assert await g.nudge() == Vec2(3.0, 4.0)
    finally:
        await client.close_async()
        await silo.stop()
