"""Async utility tests (Core/Async/* analog: retry, BatchWorker,
AsyncSerialExecutor, AsyncPipeline)."""

import asyncio

import pytest

from orleans_tpu.core import (
    AsyncPipeline,
    AsyncSerialExecutor,
    BatchWorker,
    ExponentialBackoff,
    retry,
)

FAST_BACKOFF = ExponentialBackoff(min_delay=0.001, max_delay=0.005)


async def test_retry_succeeds_after_transient_failures():
    calls = []

    async def flaky(attempt):
        calls.append(attempt)
        if attempt < 2:
            raise ConnectionError("transient")
        return "ok"

    assert await retry(flaky, max_attempts=5, backoff=FAST_BACKOFF) == "ok"
    assert calls == [0, 1, 2]


async def test_retry_gives_up_after_max_attempts():
    async def always_fails():
        raise ValueError("nope")

    with pytest.raises(ValueError):
        await retry(always_fails, max_attempts=3, backoff=FAST_BACKOFF)


async def test_retry_respects_filter():
    calls = []

    async def fails():
        calls.append(1)
        raise KeyError("fatal")

    with pytest.raises(KeyError):
        await retry(fails, max_attempts=5, backoff=FAST_BACKOFF,
                    retry_on=ConnectionError)
    assert len(calls) == 1  # non-matching error is not retried


async def test_batch_worker_coalesces():
    runs = []

    async def work():
        runs.append(1)
        await asyncio.sleep(0.02)

    w = BatchWorker(work)
    # burst of notifies while the first batch runs → exactly one more run
    w.notify()
    await asyncio.sleep(0.005)
    for _ in range(10):
        w.notify()
    await w.wait_idle()
    assert len(runs) == 2, f"expected coalescing to 2 runs, got {len(runs)}"
    # new notify after idle runs again
    await w.notify_and_wait()
    assert len(runs) == 3
    w.close()


async def test_serial_executor_is_serial_and_ordered():
    order = []
    running = 0
    max_running = 0

    async def job(i):
        nonlocal running, max_running
        running += 1
        max_running = max(max_running, running)
        await asyncio.sleep(0.001)
        order.append(i)
        running -= 1
        return i

    ex = AsyncSerialExecutor()
    results = await asyncio.gather(
        *(ex.execute(lambda i=i: job(i)) for i in range(10)))
    assert results == list(range(10))
    assert order == list(range(10))
    assert max_running == 1


async def test_serial_executor_propagates_errors():
    ex = AsyncSerialExecutor()

    async def boom():
        raise RuntimeError("x")

    with pytest.raises(RuntimeError):
        await ex.execute(boom)
    # executor still works afterwards
    async def ok():
        return 42
    assert await ex.execute(ok) == 42


async def test_pipeline_bounds_concurrency():
    running = 0
    max_running = 0

    async def job():
        nonlocal running, max_running
        running += 1
        max_running = max(max_running, running)
        await asyncio.sleep(0.005)
        running -= 1

    p = AsyncPipeline(capacity=3)
    for _ in range(12):
        await p.add(job())
    await p.wait_complete()
    assert max_running <= 3
    assert p.count == 0


async def test_pipeline_surfaces_errors():
    async def boom():
        raise ValueError("pipeline error")

    async def ok():
        await asyncio.sleep(0.001)

    p = AsyncPipeline(capacity=2)
    await p.add(ok())
    await p.add(boom())
    await p.add(ok())
    with pytest.raises(ValueError):
        await p.wait_complete()
