"""Wound-wait entry, transactional interleaving, and the direct
always-interleave call path (round-3 contention rework).

The contract under test: pessimistic workspace entry with wound-wait
deadlock avoidance (orleans_tpu/transactions/state.py), conflict retries
keeping their original priority ts (manager.transactional), transactional
methods interleaving so lock waits never block a mailbox, and the
in-silo direct path for always-interleave calls preserving copy isolation
(silo.InsideRuntimeClient.try_direct_interleave).
"""

import asyncio

import pytest

from orleans_tpu.core.errors import TransactionConflictError
from orleans_tpu.runtime import ClusterClient, Grain, SiloBuilder
from orleans_tpu.runtime.grain import always_interleave
from orleans_tpu.transactions import (TransactionalGrain, TransactionalState,
                                      add_transactions, transactional)
from orleans_tpu.transactions.context import TransactionInfo

START = 1000


class Account(TransactionalGrain):
    def __init__(self):
        self.balance = TransactionalState("balance", default=START)

    @transactional
    async def deposit(self, n):
        await self.balance.set(await self.balance.get() + n)

    @transactional
    async def withdraw(self, n):
        await self.balance.set(await self.balance.get() - n)

    async def get_balance(self):
        return await self.balance.get()


class SlowMover(TransactionalGrain):
    """Transfer that parks mid-transaction so another txn can collide."""

    @transactional
    async def transfer_slow(self, src, dst, n, hold):
        await self.get_grain(Account, src).withdraw(n)
        await asyncio.sleep(hold)  # hold the src workspace open
        await self.get_grain(Account, dst).deposit(n)

    @transactional
    async def transfer(self, src, dst, n):
        await self.get_grain(Account, src).withdraw(n)
        await self.get_grain(Account, dst).deposit(n)


async def _cluster():
    silo = add_transactions(
        SiloBuilder().with_name("ww").add_grains(Account, SlowMover)).build()
    await silo.start()
    client = await ClusterClient(silo.fabric).connect()
    return silo, client


async def test_opposite_order_transfers_no_deadlock_conservation():
    """The classic 2PC deadlock shape: A→B and B→A concurrently, many
    times over. Wound-wait must resolve every collision without either
    transaction timing out, and money is conserved."""
    silo, client = await _cluster()
    try:
        m1 = client.get_grain(SlowMover, 1)
        m2 = client.get_grain(SlowMover, 2)
        await asyncio.gather(*(
            coro for i in range(25)
            for coro in (m1.transfer(0, 1, 1), m2.transfer(1, 0, 1))
        ))
        b0 = await client.get_grain(Account, 0).get_balance()
        b1 = await client.get_grain(Account, 1).get_balance()
        assert b0 + b1 == 2 * START
    finally:
        await client.close_async()
        await silo.stop()


async def test_older_transaction_wounds_younger_holder():
    """An older transaction arriving at a younger holder's state proceeds
    immediately (wound-and-enter); the wounded younger retries and still
    commits — both transfers land, conservation holds."""
    silo, client = await _cluster()
    try:
        m1 = client.get_grain(SlowMover, 1)
        m2 = client.get_grain(SlowMover, 2)

        async def young_then_old():
            # m2's txn starts LATER (younger)... but we start the slow one
            # first so it holds account 2's workspace when m1 arrives
            slow = asyncio.ensure_future(m2.transfer_slow(2, 3, 5, 0.05))
            await asyncio.sleep(0.01)
            # m1 starts after m2 → m1 is YOUNGER than m2 here; invert by
            # letting m1 be the later-running but both directions must
            # settle regardless — the assertion is progress + conservation
            await m1.transfer(2, 3, 7)
            await slow

        await young_then_old()
        b2 = await client.get_grain(Account, 2).get_balance()
        b3 = await client.get_grain(Account, 3).get_balance()
        assert b2 == START - 12 and b3 == START + 12
    finally:
        await client.close_async()
        await silo.stop()


async def test_conflict_retry_keeps_priority_ts():
    """The root scope must reuse the original wait-die/wound-wait priority
    on conflict retries (aging), not mint a fresh one."""
    silo, client = await _cluster()
    try:
        seen_ts = []
        real_start = silo.transactions.start

        def spying_start(timeout=10.0, priority_ts=None):
            info = real_start(timeout=timeout, priority_ts=priority_ts)
            seen_ts.append(info.ts)
            return info

        silo.transactions.start = spying_start

        calls = {"n": 0}

        class Flaky(TransactionalGrain):
            @transactional
            async def op(self):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise TransactionConflictError("injected conflict")
                return "ok"

        silo.registry.register(Flaky)
        out = await client.get_grain(Flaky, "f").op()
        assert out == "ok"
        assert calls["n"] == 2
        assert len(seen_ts) >= 2 and seen_ts[0] == seen_ts[1], \
            "retry must carry the original priority ts"
    finally:
        silo.transactions.start = real_start
        await client.close_async()
        await silo.stop()


async def test_transactional_methods_interleave():
    """A lock wait inside one transaction must not block the activation's
    mailbox for other transactional calls."""

    class Parker(TransactionalGrain):
        def __init__(self):
            self.state = TransactionalState("s", default=0)
            self.gate = asyncio.Event()
            self.entered = asyncio.Event()

        @transactional
        async def hold(self):
            await self.state.get()
            self.entered.set()
            await asyncio.wait_for(self.gate.wait(), 5)

        @transactional
        async def quick(self):
            return "in"  # touches no state: must run while hold() parks

        @always_interleave
        async def release(self):
            self.gate.set()

    silo = add_transactions(
        SiloBuilder().with_name("il").add_grains(Parker)).build()
    await silo.start()
    client = await ClusterClient(silo.fabric).connect()
    try:
        g = client.get_grain(Parker, "p")
        holder = asyncio.ensure_future(g.hold())
        # wait until hold() is parked inside its turn
        acts = None
        for _ in range(200):
            await asyncio.sleep(0.005)
            from orleans_tpu.core.ids import GrainId
            from orleans_tpu.runtime.grain import grain_type_of
            acts = silo.catalog.by_grain.get(
                GrainId.for_grain(grain_type_of(Parker), "p"))
            if acts and acts[0].grain_instance.entered.is_set():
                break
        assert acts, "activation never appeared"
        # quick() must complete while hold() is still parked
        assert await asyncio.wait_for(g.quick(), timeout=1) == "in"
        await g.release()
        await holder
    finally:
        await client.close_async()
        await silo.stop()


async def test_direct_interleave_path_copy_isolates():
    """The in-silo direct path for always-interleave calls must keep the
    messaging path's copy isolation: caller mutations after the call
    cannot leak into the callee, nor callee state out to the caller."""

    class Holder(Grain):
        def __init__(self):
            self.items = []

        @always_interleave
        async def put(self, xs):
            self.items.append(xs)
            return xs

        @always_interleave
        async def peek(self):
            return self.items[-1]

    class Caller(Grain):
        async def drive(self):
            h = self.get_grain(Holder, "h")
            payload = [1, 2]
            await h.put(payload)
            payload.append(3)          # caller-side mutation post-call
            stored = await h.peek()
            # callee must have its own copy, not the mutated list
            assert stored == [1, 2], stored
            stored.append(99)          # mutate the returned copy
            again = await h.peek()
            assert again == [1, 2], again  # callee state untouched
            return "isolated"

    silo = SiloBuilder().with_name("dc").add_grains(Holder, Caller).build()
    await silo.start()
    client = await ClusterClient(silo.fabric).connect()
    try:
        assert await client.get_grain(Caller, "c").drive() == "isolated"
    finally:
        await client.close_async()
        await silo.stop()


async def test_tight_call_loop_does_not_starve_background_tasks():
    """Each RPC yields the event loop at least once (the fairness contract
    of RuntimeClient._await_response) even when the whole call completes
    inline — a background task must keep ticking during a tight call loop."""

    class Echo(Grain):
        async def ping(self, x):
            return x

    silo = SiloBuilder().with_name("fair").add_grains(Echo).build()
    await silo.start()
    client = await ClusterClient(silo.fabric).connect()
    try:
        g = client.get_grain(Echo, 0)
        await g.ping(0)
        ticks = 0

        async def ticker():
            nonlocal ticks
            while True:
                ticks += 1
                await asyncio.sleep(0)

        t = asyncio.ensure_future(ticker())
        for i in range(2000):
            await g.ping(i)
        t.cancel()
        assert ticks > 500, f"background task starved: {ticks} ticks"
    finally:
        await client.close_async()
        await silo.stop()
