"""A real multi-OS-process cluster: a silo in a child process, joined via
the shared file membership table, talking TCP to a silo in this process.

Everything else in the suite exercises the cross-process CODE PATHS
(separate fabrics, real sockets) within one interpreter; this proves the
actual process boundary: separate GILs, separate interners, wire frames
decoded by a process that never saw the sender's objects, and real
SIGKILL death detected by probes.
"""

import asyncio
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from orleans_tpu.membership import FileMembershipTable, join_cluster
from orleans_tpu.runtime import Grain, SiloBuilder
from orleans_tpu.runtime.socket_fabric import GatewayClient, SocketFabric

pytestmark = pytest.mark.skipif(sys.platform == "win32", reason="posix only")


class EchoGrain(Grain):
    async def echo(self, x):
        return f"{self.primary_key}:{x}"

    async def where(self) -> str:
        return self._activation.runtime.silo_address.endpoint


# one source of truth for liveness tuning — asymmetric probe timings
# between the two processes would make kill detection flaky
LIVENESS = dict(membership_probe_period=0.25,
                membership_probe_timeout=1.0,
                membership_missed_probes_limit=2,
                membership_votes_needed=1,
                membership_iam_alive_period=0.5,
                membership_refresh_period=0.2)


CHILD = textwrap.dedent("""
    import asyncio, sys
    sys.path.insert(0, {repo!r})
    from orleans_tpu.membership import FileMembershipTable, join_cluster
    from orleans_tpu.runtime import Grain, SiloBuilder
    from orleans_tpu.runtime.socket_fabric import SocketFabric

    class EchoGrain(Grain):
        async def echo(self, x):
            return f"{{self.primary_key}}:{{x}}"

        async def where(self) -> str:
            return self._activation.runtime.silo_address.endpoint

    async def main():
        table = FileMembershipTable({table!r})
        silo = (SiloBuilder().with_name("child").with_fabric(SocketFabric())
                .add_grains(EchoGrain)
                .with_config(**{cfg!r})).build()
        join_cluster(silo, table)
        await silo.start()
        print("CHILD-READY", silo.silo_address.endpoint, flush=True)
        await asyncio.sleep(3600)

    asyncio.run(main())
""")


async def _converged(silo, n):
    """Parent-side convergence: membership and placement views both at n."""
    while len(silo.membership.active) != n or \
            len(silo.locator.alive_list) != n:
        await asyncio.sleep(0.05)


async def _spread_over_both(client, parent_ep, deadline_s=20.0):
    """Touch grains until placement lands some in EACH process and return
    (child_endpoint, child_keys). The parent cannot observe the CHILD's
    membership view, and a child that has not yet refreshed to see the
    parent places its directory share on itself — so the first batch can
    legitimately land one-sided under load. The subject of these tests is
    the process boundary, not first-try placement, so spread is awaited
    with fresh keys per attempt."""
    deadline = time.monotonic() + deadline_s
    base = 0
    while True:
        keys = list(range(base, base + 32))
        wheres = await asyncio.gather(
            *(client.get_grain(EchoGrain, k).where() for k in keys))
        endpoints = set(wheres)
        if len(endpoints) == 2:
            child_ep = next(e for e in endpoints if e != parent_ep)
            return child_ep, [k for k, w in zip(keys, wheres)
                              if w == child_ep]
        assert time.monotonic() < deadline, \
            f"placement never spread over both processes: {endpoints}"
        base += 32
        await asyncio.sleep(0.5)


async def test_mixed_build_cluster_negotiates_codec(tmp_path):
    """A silo whose native hotwire build is unavailable must interoperate
    with native-enabled peers: the handshake advertises codec support and
    each link falls back to pickle toward a pickle-only peer. Without the
    negotiation, every parent→child frame is 0xA7-hotwire and the child
    drops it (calls time out)."""
    from orleans_tpu.core import serialization as ser
    if ser._hotwire is None:
        pytest.skip("native codec unavailable in this build")

    table_path = str(tmp_path / "mbr.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.Popen(
        [sys.executable, "-c",
         CHILD.format(repo=repo, table=table_path, cfg=LIVENESS)],
        stdout=subprocess.PIPE, stderr=open(tmp_path / "child.err", "w"),
        text=True, env={**os.environ, "JAX_PLATFORMS": "cpu",
                        "ORLEANS_TPU_NATIVE": "0"})
    silo = None
    client = None
    try:
        loop = asyncio.get_running_loop()
        line = await asyncio.wait_for(
            loop.run_in_executor(None, child.stdout.readline), timeout=60)
        assert line.startswith("CHILD-READY"), (
            line, (tmp_path / "child.err").read_text()[-2000:])

        table = FileMembershipTable(table_path)
        silo = (SiloBuilder().with_name("parent").with_fabric(SocketFabric())
                .add_grains(EchoGrain)
                .with_config(**LIVENESS)).build()
        join_cluster(silo, table)
        await silo.start()

        await asyncio.wait_for(_converged(silo, 2), timeout=15)

        client = await GatewayClient(
            [silo.silo_address.endpoint], response_timeout=10.0).connect()

        child_ep, child_keys = await _spread_over_both(
            client, silo.silo_address.endpoint)

        # round-trips through the pickle-only child prove both directions
        # negotiated down (a hotwire frame would be undecodable there)
        outs = await asyncio.gather(
            *(client.get_grain(EchoGrain, k).echo("mixed")
              for k in child_keys))
        assert outs == [f"{k}:mixed" for k in child_keys]
    finally:
        try:
            if child.poll() is None:
                child.kill()
            child.wait(timeout=10)
        finally:
            try:
                if client is not None:
                    await client.close_async()
            finally:
                if silo is not None:
                    await silo.stop()


# --------------------------------------------------------------------------
# worker_procs silos (ISSUE 18): forked SO_REUSEPORT workers + shm rings
# --------------------------------------------------------------------------

def _vector_grain():
    """Deterministic accumulating vector grain, built lazily so the jax
    import stays inside the tests that need it. ``add`` folds each call's
    float into per-key state — the SAME call sequence must produce
    bit-identical accumulator reads whether the calls reach the engine
    in-process (worker_procs=1) or across the shm staging rings
    (worker_procs=2)."""
    import jax.numpy as jnp

    from orleans_tpu.dispatch import VectorGrain, actor_method

    class AccumVec(VectorGrain):
        STATE = {"acc": (jnp.float32, ()), "n": (jnp.int32, ())}

        @staticmethod
        def initial_state(key_hash):
            return {"acc": jnp.float32(0), "n": jnp.int32(0)}

        @actor_method(args={"x": (jnp.float32, ())})
        def add(state, args):
            new = {"acc": state["acc"] + args["x"], "n": state["n"] + 1}
            return new, new["acc"]

    return AccumVec


def _build_mp_silo(table_path, vec_cls, worker_procs, name="mp"):
    from orleans_tpu.dispatch import add_vector_grains
    from orleans_tpu.parallel import make_mesh

    fabric = SocketFabric()
    b = (SiloBuilder().with_name(name).with_fabric(fabric)
         .add_grains(EchoGrain)
         .with_config(**LIVENESS, worker_procs=worker_procs))
    add_vector_grains(b, vec_cls, mesh=make_mesh(8), capacity_per_shard=32)
    silo = b.build()
    join_cluster(silo, FileMembershipTable(table_path))
    return silo


async def _accum_sequence(endpoint, vec_cls, n_clients=4, keys=24,
                          rounds=3):
    """The shared parity workload: ``rounds`` waves of one ``add`` per
    key, keys striped over ``n_clients`` gateway connections, results
    collected IN ORDER. Returns the flat list of accumulator reads."""
    clients = []
    out = []
    try:
        for _ in range(n_clients):
            clients.append(await GatewayClient(
                [endpoint], response_timeout=15.0).connect())
        for r in range(rounds):
            vals = await asyncio.gather(*(
                clients[k % n_clients].get_grain(vec_cls, k)
                .add(x=float(k) * 0.5 + r)
                for k in range(keys)))
            out.extend(float(v) for v in vals)
    finally:
        for c in clients:
            await c.close_async()
    return out


async def test_worker_procs_vector_parity_debug_pool(tmp_path):
    """Bit-for-bit parity (the ISSUE 18 acceptance point): the same call
    sequence against worker_procs=1 and worker_procs=2 silos produces
    IDENTICAL accumulator reads — the shm staging rings + proxy
    re-address + call_packed unpack change where the bytes travel, never
    what the engine computes. Runs under debug pool-poisoning
    (ORLEANS_TPU_DEBUG_POOL): forked workers inherit the flag, so a
    recycled message shell touched by the relay/proxy paths would
    assert, in any of the three processes."""
    from orleans_tpu.core.message import set_debug_pool

    vec_cls = _vector_grain()
    prev = set_debug_pool(True)
    try:
        results = {}
        for procs in (1, 2):
            silo = _build_mp_silo(str(tmp_path / f"mbr{procs}.json"),
                                  vec_cls, procs, name=f"par{procs}")
            await silo.start()
            try:
                results[procs] = await _accum_sequence(
                    silo.gateway_endpoint, vec_cls)
                if procs == 2:
                    d = silo.workers.describe()
                    # clean-shutdown accounting: every decoded-and-staged
                    # record drained, every completion delivered (the
                    # counters are single-writer cumulative — torn-free)
                    assert all(w["req_pushed"] == w["req_drained"] and
                               w["resp_pushed"] == w["resp_drained"]
                               for w in d["workers"]), d
                    # the vector traffic actually crossed the rings:
                    # every one of the 24 keys x 3 rounds staged exactly
                    # one message (vec records count n_msgs=1 per call;
                    # route/ready records count 0)
                    assert sum(w["req_pushed"]
                               for w in d["workers"]) == 24 * 3, d
            finally:
                await silo.stop()
        assert results[2] == results[1], (
            "shm-ring vector path diverged from the in-process path")
    finally:
        set_debug_pool(prev)


async def test_worker_sigkill_rebalance(tmp_path):
    """SIGKILL one worker mid-traffic: the kernel stops handing its
    accept share out (new connections land on the survivor), the owner's
    membership probes declare the worker silo dead, the supervisor drops
    its relay routes, and traffic through the survivor — host and vector
    — keeps answering. Clean shutdown afterwards still accounts every
    staged record (pushed == drained on the survivor's rings)."""
    vec_cls = _vector_grain()
    silo = _build_mp_silo(str(tmp_path / "mbr.json"), vec_cls, 2,
                          name="killmp")
    await silo.start()
    clients = []
    try:
        # pre-kill traffic over several connections (some will be pinned
        # to the worker we are about to kill — that is the point)
        for _ in range(4):
            clients.append(await GatewayClient(
                [silo.gateway_endpoint], response_timeout=15.0).connect())
        vals = await asyncio.gather(*(
            clients[k % 4].get_grain(vec_cls, k).add(x=1.0)
            for k in range(16)))
        assert [float(v) for v in vals] == [1.0] * 16

        d = silo.workers.describe()
        assert sum(w["client_routes"] for w in d["workers"]) == 4
        victim = d["workers"][0]
        survivor = d["workers"][1]
        os.kill(victim["pid"], signal.SIGKILL)

        # the supervisor's reaper notices the death and the owner's
        # probes declare the worker silo dead (directory convergence)
        async def worker_reaped():
            while True:
                dd = silo.workers.describe()
                if not dd["workers"][0]["alive"]:
                    return
                await asyncio.sleep(0.1)
        await asyncio.wait_for(worker_reaped(), timeout=10)

        async def declared_dead():
            while not any(victim["silo"] in str(a)
                          for a in silo.membership.dead):
                await asyncio.sleep(0.1)
        await asyncio.wait_for(declared_dead(), timeout=20)

        # new connections can only land on the survivor (the dead
        # worker's SO_REUSEPORT listener died with it) and must answer
        fresh = []
        for _ in range(3):
            fresh.append(await GatewayClient(
                [silo.gateway_endpoint], response_timeout=15.0).connect())
        clients.extend(fresh)
        vals = await asyncio.gather(*(
            c.get_grain(vec_cls, 100 + i).add(x=2.0)
            for i, c in enumerate(fresh)))
        assert [float(v) for v in vals] == [2.0] * 3
        outs = await asyncio.gather(*(
            c.get_grain(EchoGrain, 200 + i).echo("hi")
            for i, c in enumerate(fresh)))
        assert outs == [f"{200 + i}:hi" for i in range(3)]

        d2 = silo.workers.describe()
        # accept rebalancing: every fresh connection pinned to the
        # survivor, and the dead worker's relay routes were dropped
        assert d2["workers"][0]["client_routes"] == 0, d2
        assert d2["workers"][1]["client_routes"] >= 3, d2
        assert d2["workers"][1]["alive"]
        # the survivor's rings still account every decoded message
        assert survivor["silo"] == d2["workers"][1]["silo"]
        w = d2["workers"][1]
        assert w["req_pushed"] == w["req_drained"], d2
        assert w["resp_pushed"] == w["resp_drained"], d2
    finally:
        for c in clients:
            try:
                await c.close_async()
            except Exception:
                pass
        await silo.stop()


async def test_cross_os_process_cluster_and_kill(tmp_path):
    table_path = str(tmp_path / "mbr.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.Popen(
        [sys.executable, "-c",
         CHILD.format(repo=repo, table=table_path, cfg=LIVENESS)],
        stdout=subprocess.PIPE, stderr=open(tmp_path / "child.err", "w"),
        text=True, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    silo = None
    client = None
    try:
        # wait for the child silo to come up
        loop = asyncio.get_running_loop()
        line = await asyncio.wait_for(
            loop.run_in_executor(None, child.stdout.readline), timeout=60)
        assert line.startswith("CHILD-READY"), (
            line, (tmp_path / "child.err").read_text()[-2000:])

        table = FileMembershipTable(table_path)
        silo = (SiloBuilder().with_name("parent").with_fabric(SocketFabric())
                .add_grains(EchoGrain)
                .with_config(**LIVENESS)).build()
        join_cluster(silo, table)
        await silo.start()

        await asyncio.wait_for(_converged(silo, 2), timeout=15)

        client = await GatewayClient(
            [silo.silo_address.endpoint], response_timeout=10.0).connect()

        # touch grains until placement lands some IN THE CHILD PROCESS
        child_ep, child_keys = await _spread_over_both(
            client, silo.silo_address.endpoint)

        # calls to child-hosted grains cross the OS-process boundary
        outs = await asyncio.gather(
            *(client.get_grain(EchoGrain, k).echo("hi") for k in child_keys))
        assert outs == [f"{k}:hi" for k in child_keys]

        # SIGKILL the child: probes must declare it dead, and its grains
        # must re-place onto the survivor and answer again
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=10)

        async def declared_dead():
            while not silo.membership.dead:
                await asyncio.sleep(0.1)
        await asyncio.wait_for(declared_dead(), timeout=20)

        k = child_keys[0]
        out = await asyncio.wait_for(
            client.get_grain(EchoGrain, k).echo("back"), timeout=15)
        assert out == f"{k}:back"
        assert (await client.get_grain(EchoGrain, k).where()) == \
            silo.silo_address.endpoint
    finally:
        # reap the child FIRST: a hanging client/silo teardown must not
        # leak a process holding the port + membership file
        try:
            if child.poll() is None:
                child.kill()
            child.wait(timeout=10)
        finally:
            try:
                if client is not None:
                    await client.close_async()
            finally:
                if silo is not None:
                    await silo.stop()


# --------------------------------------------------------------------------
# cross-process observability (ISSUE 20): trace context over the rings,
# per-worker ledger attribution, and the cluster-wide span merge
# --------------------------------------------------------------------------

def _build_obs_mp_silo(table_path, vec_cls, worker_procs, name="obsmp"):
    """worker_procs silo with the FULL observability stack + management:
    trace context must survive the shm ring hop (workers get their own
    SiloControl so the cluster fan-outs reach every process)."""
    from orleans_tpu.dispatch import add_vector_grains
    from orleans_tpu.management import add_management
    from orleans_tpu.parallel import make_mesh

    fabric = SocketFabric()
    b = (SiloBuilder().with_name(name).with_fabric(fabric)
         .add_grains(EchoGrain)
         .with_config(**LIVENESS, worker_procs=worker_procs,
                      metrics_enabled=True, trace_enabled=True,
                      trace_sample_rate=0.0, ledger_enabled=True))
    add_vector_grains(b, vec_cls, mesh=make_mesh(8), capacity_per_shard=32)
    add_management(b)
    silo = b.build()
    join_cluster(silo, FileMembershipTable(table_path))
    return silo


async def test_worker_procs_trace_waterfall(tmp_path):
    """The ISSUE 20 acceptance: a client-rooted request through a worker
    process yields ONE trace whose cluster-merged spans cover >= 95% of
    the request wall as contiguous segments — client network leg, shm
    staging-ring dwell (worker push → owner pop), owner queue-wait +
    device tick, response-ring dwell, response network leg. Before this
    PR the trace went dark between the worker's ingress and the owner's
    engine: the ring hop carried no trace context."""
    from benchmarks.multiproc_attribution import waterfall_coverage
    from orleans_tpu.management import ManagementGrain

    vec_cls = _vector_grain()
    silo = _build_obs_mp_silo(str(tmp_path / "mbr.json"), vec_cls, 2)
    await silo.start()
    client = None
    try:
        client = await GatewayClient(
            [silo.gateway_endpoint], response_timeout=15.0).connect()
        # warmup: activate the key + compile the kernel so the traced
        # request measures the steady-state path, not one-time JIT
        await client.get_grain(vec_cls, 0).add(x=1.0)

        client.enable_tracing(sample_rate=1.0, name="mp-client")
        assert float(await client.get_grain(vec_cls, 0).add(x=2.0)) == 3.0
        await asyncio.sleep(0.1)  # let done-callbacks close their spans
        cspans = client.tracer.snapshot()
        tids = [s["trace_id"] for s in cspans if s["kind"] == "client"]
        assert len(tids) == 1, cspans  # exactly one client-rooted trace
        tid = tids[0]

        # cluster-wide merge: the owner AND both workers answer the span
        # fan-out (workers run their own SiloControl since this PR)
        mgmt = client.get_grain(ManagementGrain, 0)
        spans = cspans + await mgmt.get_trace_spans(tid)
        wf = waterfall_coverage(spans, tid)

        names = {s["name"] for s in wf["segments"]}
        assert "shm.staging_ring" in names, wf
        assert "shm.response_ring" in names, wf
        assert "engine.queue_wait" in names, wf
        assert any(n.startswith("tick ") for n in names), wf
        assert {"ring", "network", "server", "device_tick"} <= \
            set(wf["kinds"]), wf
        # contiguous coverage of the measured request wall
        assert wf["coverage"] >= 0.95, wf
        # waterfall order: staging dwell precedes the tick, the response
        # ring leg outlives it (push happens at tick completion)
        seg = {s["name"]: s for s in wf["segments"]}
        tick = next(s for s in wf["segments"]
                    if s["name"].startswith("tick "))
        assert seg["shm.staging_ring"]["offset_us"] <= tick["offset_us"]
        resp = seg["shm.response_ring"]
        assert resp["offset_us"] + resp["dur_us"] >= \
            tick["offset_us"] + tick["dur_us"]
        # the spans name >= 3 distinct silos (client, owner, worker) —
        # the Perfetto export keys its process tracks by span silo, so
        # the waterfall renders one track per OS process for free
        assert len({s["silo"] for s in spans
                    if s["trace_id"] == tid}) >= 3, spans
    finally:
        if client is not None:
            await client.close_async()
        await silo.stop()


async def test_worker_procs_ledger_attribution(tmp_path):
    """Per-worker cost attribution (ISSUE 20 satellite): device rows
    charged on the owner's engine land on the ORIGINATING worker's
    ``procs`` row (exactly its staged message count), the owner's wire
    charges are keyed by worker origin, and the cluster merge is
    fold-order independent."""
    from orleans_tpu.management import ManagementGrain
    from orleans_tpu.observability.ledger import CostLedger

    vec_cls = _vector_grain()
    silo = _build_obs_mp_silo(str(tmp_path / "mbr.json"), vec_cls, 2,
                              name="ledmp")
    await silo.start()
    clients = []
    try:
        for _ in range(4):
            clients.append(await GatewayClient(
                [silo.gateway_endpoint], response_timeout=15.0).connect())
        vals = await asyncio.gather(*(
            clients[k % 4].get_grain(vec_cls, k).add(x=1.0)
            for k in range(24)))
        assert [float(v) for v in vals] == [1.0] * 24

        # ground truth from the ring counters: how many vector messages
        # each worker actually staged (single-writer cumulative)
        d = silo.workers.describe()
        pushed = {f"worker-{w['index']}": w["req_pushed"]
                  for w in d["workers"]}
        assert sum(pushed.values()) == 24, d

        # the ring counters are live MetricsSampler gauges (ISSUE 20):
        # summed across workers, evaluated at snapshot time
        gauges = silo.stats.snapshot()["gauges"]
        assert gauges["workers.alive"] == 2, gauges
        assert gauges["workers.req_drained"] == 24, gauges
        assert gauges["workers.req_backlog"] == 0, gauges
        assert gauges["workers.resp_pushed"] == 24, gauges

        mgmt = clients[0].get_grain(ManagementGrain, 0)
        led = await mgmt.get_cluster_ledger(5)
        procs = led["procs"]
        # every device row charged to exactly the worker that staged it
        assert set(procs) == {o for o, n in pushed.items() if n}, led
        for origin, (rows, secs) in procs.items():
            assert rows == pushed[origin], (origin, procs, pushed)
            assert secs > 0, (origin, procs)
        # the owner's shm wire accounting is keyed by the same origin
        for origin in procs:
            rx, tx = led["wire"][origin]
            assert rx > 0 and tx > 0, (origin, led["wire"])
        # deterministic merge: silo fold order cannot change the answer
        snaps = [s for s in led["per_silo"].values() if s]
        a = CostLedger.merge(snaps)
        z = CostLedger.merge(list(reversed(snaps)))
        assert a["procs"] == z["procs"] and a["wire"] == z["wire"]
    finally:
        for c in clients:
            try:
                await c.close_async()
            except Exception:
                pass
        await silo.stop()
