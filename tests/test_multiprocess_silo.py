"""A real multi-OS-process cluster: a silo in a child process, joined via
the shared file membership table, talking TCP to a silo in this process.

Everything else in the suite exercises the cross-process CODE PATHS
(separate fabrics, real sockets) within one interpreter; this proves the
actual process boundary: separate GILs, separate interners, wire frames
decoded by a process that never saw the sender's objects, and real
SIGKILL death detected by probes.
"""

import asyncio
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from orleans_tpu.membership import FileMembershipTable, join_cluster
from orleans_tpu.runtime import Grain, SiloBuilder
from orleans_tpu.runtime.socket_fabric import GatewayClient, SocketFabric

pytestmark = pytest.mark.skipif(sys.platform == "win32", reason="posix only")


class EchoGrain(Grain):
    async def echo(self, x):
        return f"{self.primary_key}:{x}"

    async def where(self) -> str:
        return self._activation.runtime.silo_address.endpoint


# one source of truth for liveness tuning — asymmetric probe timings
# between the two processes would make kill detection flaky
LIVENESS = dict(membership_probe_period=0.25,
                membership_probe_timeout=1.0,
                membership_missed_probes_limit=2,
                membership_votes_needed=1,
                membership_iam_alive_period=0.5,
                membership_refresh_period=0.2)


CHILD = textwrap.dedent("""
    import asyncio, sys
    sys.path.insert(0, {repo!r})
    from orleans_tpu.membership import FileMembershipTable, join_cluster
    from orleans_tpu.runtime import Grain, SiloBuilder
    from orleans_tpu.runtime.socket_fabric import SocketFabric

    class EchoGrain(Grain):
        async def echo(self, x):
            return f"{{self.primary_key}}:{{x}}"

        async def where(self) -> str:
            return self._activation.runtime.silo_address.endpoint

    async def main():
        table = FileMembershipTable({table!r})
        silo = (SiloBuilder().with_name("child").with_fabric(SocketFabric())
                .add_grains(EchoGrain)
                .with_config(**{cfg!r})).build()
        join_cluster(silo, table)
        await silo.start()
        print("CHILD-READY", silo.silo_address.endpoint, flush=True)
        await asyncio.sleep(3600)

    asyncio.run(main())
""")


async def _converged(silo, n):
    """Parent-side convergence: membership and placement views both at n."""
    while len(silo.membership.active) != n or \
            len(silo.locator.alive_list) != n:
        await asyncio.sleep(0.05)


async def _spread_over_both(client, parent_ep, deadline_s=20.0):
    """Touch grains until placement lands some in EACH process and return
    (child_endpoint, child_keys). The parent cannot observe the CHILD's
    membership view, and a child that has not yet refreshed to see the
    parent places its directory share on itself — so the first batch can
    legitimately land one-sided under load. The subject of these tests is
    the process boundary, not first-try placement, so spread is awaited
    with fresh keys per attempt."""
    deadline = time.monotonic() + deadline_s
    base = 0
    while True:
        keys = list(range(base, base + 32))
        wheres = await asyncio.gather(
            *(client.get_grain(EchoGrain, k).where() for k in keys))
        endpoints = set(wheres)
        if len(endpoints) == 2:
            child_ep = next(e for e in endpoints if e != parent_ep)
            return child_ep, [k for k, w in zip(keys, wheres)
                              if w == child_ep]
        assert time.monotonic() < deadline, \
            f"placement never spread over both processes: {endpoints}"
        base += 32
        await asyncio.sleep(0.5)


async def test_mixed_build_cluster_negotiates_codec(tmp_path):
    """A silo whose native hotwire build is unavailable must interoperate
    with native-enabled peers: the handshake advertises codec support and
    each link falls back to pickle toward a pickle-only peer. Without the
    negotiation, every parent→child frame is 0xA7-hotwire and the child
    drops it (calls time out)."""
    from orleans_tpu.core import serialization as ser
    if ser._hotwire is None:
        pytest.skip("native codec unavailable in this build")

    table_path = str(tmp_path / "mbr.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.Popen(
        [sys.executable, "-c",
         CHILD.format(repo=repo, table=table_path, cfg=LIVENESS)],
        stdout=subprocess.PIPE, stderr=open(tmp_path / "child.err", "w"),
        text=True, env={**os.environ, "JAX_PLATFORMS": "cpu",
                        "ORLEANS_TPU_NATIVE": "0"})
    silo = None
    client = None
    try:
        loop = asyncio.get_running_loop()
        line = await asyncio.wait_for(
            loop.run_in_executor(None, child.stdout.readline), timeout=60)
        assert line.startswith("CHILD-READY"), (
            line, (tmp_path / "child.err").read_text()[-2000:])

        table = FileMembershipTable(table_path)
        silo = (SiloBuilder().with_name("parent").with_fabric(SocketFabric())
                .add_grains(EchoGrain)
                .with_config(**LIVENESS)).build()
        join_cluster(silo, table)
        await silo.start()

        await asyncio.wait_for(_converged(silo, 2), timeout=15)

        client = await GatewayClient(
            [silo.silo_address.endpoint], response_timeout=10.0).connect()

        child_ep, child_keys = await _spread_over_both(
            client, silo.silo_address.endpoint)

        # round-trips through the pickle-only child prove both directions
        # negotiated down (a hotwire frame would be undecodable there)
        outs = await asyncio.gather(
            *(client.get_grain(EchoGrain, k).echo("mixed")
              for k in child_keys))
        assert outs == [f"{k}:mixed" for k in child_keys]
    finally:
        try:
            if child.poll() is None:
                child.kill()
            child.wait(timeout=10)
        finally:
            try:
                if client is not None:
                    await client.close_async()
            finally:
                if silo is not None:
                    await silo.stop()


async def test_cross_os_process_cluster_and_kill(tmp_path):
    table_path = str(tmp_path / "mbr.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.Popen(
        [sys.executable, "-c",
         CHILD.format(repo=repo, table=table_path, cfg=LIVENESS)],
        stdout=subprocess.PIPE, stderr=open(tmp_path / "child.err", "w"),
        text=True, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    silo = None
    client = None
    try:
        # wait for the child silo to come up
        loop = asyncio.get_running_loop()
        line = await asyncio.wait_for(
            loop.run_in_executor(None, child.stdout.readline), timeout=60)
        assert line.startswith("CHILD-READY"), (
            line, (tmp_path / "child.err").read_text()[-2000:])

        table = FileMembershipTable(table_path)
        silo = (SiloBuilder().with_name("parent").with_fabric(SocketFabric())
                .add_grains(EchoGrain)
                .with_config(**LIVENESS)).build()
        join_cluster(silo, table)
        await silo.start()

        await asyncio.wait_for(_converged(silo, 2), timeout=15)

        client = await GatewayClient(
            [silo.silo_address.endpoint], response_timeout=10.0).connect()

        # touch grains until placement lands some IN THE CHILD PROCESS
        child_ep, child_keys = await _spread_over_both(
            client, silo.silo_address.endpoint)

        # calls to child-hosted grains cross the OS-process boundary
        outs = await asyncio.gather(
            *(client.get_grain(EchoGrain, k).echo("hi") for k in child_keys))
        assert outs == [f"{k}:hi" for k in child_keys]

        # SIGKILL the child: probes must declare it dead, and its grains
        # must re-place onto the survivor and answer again
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=10)

        async def declared_dead():
            while not silo.membership.dead:
                await asyncio.sleep(0.1)
        await asyncio.wait_for(declared_dead(), timeout=20)

        k = child_keys[0]
        out = await asyncio.wait_for(
            client.get_grain(EchoGrain, k).echo("back"), timeout=15)
        assert out == f"{k}:back"
        assert (await client.get_grain(EchoGrain, k).where()) == \
            silo.silo_address.endpoint
    finally:
        # reap the child FIRST: a hanging client/silo teardown must not
        # leak a process holding the port + membership file
        try:
            if child.poll() is None:
                child.kill()
            child.wait(timeout=10)
        finally:
            try:
                if client is not None:
                    await client.close_async()
            finally:
                if silo is not None:
                    await silo.stop()
