"""Durable stream queue adapters (file/sqlite): produce survives process
death, pulling agents resume from the durable ack cursor, rewound
subscriptions replay beyond the in-memory cache window, and a silo killed
mid-stream loses zero events (reference: AzureQueueAdapterReceiver.cs +
PersistentStreamPullingAgent.cs:350-368 — durability lives in the queue)."""

import asyncio
import time

import pytest

from orleans_tpu.membership import InMemoryMembershipTable, join_cluster
from orleans_tpu.runtime import ClusterClient, Grain, SiloBuilder
from orleans_tpu.runtime.cluster import InProcFabric
from orleans_tpu.storage import MemoryStorage
from orleans_tpu.streams import (
    FileQueueAdapter,
    SqliteQueueAdapter,
    StreamId,
    add_persistent_streams,
)

RECEIVED: dict = {}


def _adapter(kind: str, tmp_path, **kw):
    if kind == "file":
        return FileQueueAdapter(str(tmp_path / "queues"), **kw)
    return SqliteQueueAdapter(str(tmp_path / "queues.db"), **kw)


# ---------------------------------------------------------------------------
# Adapter-level semantics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["file", "sqlite"])
async def test_durable_adapter_ack_cursor_and_replay(kind, tmp_path):
    ad = _adapter(kind, tmp_path, n_queues=2)
    sid = StreamId("p", "ns", "k")
    q = ad.queue_of(sid)
    await ad.queue_message_batch(q, sid, ["a", "b"])
    await ad.queue_message_batch(q, sid, ["c"])
    await ad.queue_message_batch(q, sid, ["d", "e", "f"])

    r1 = ad.create_receiver(q)
    got = await r1.get_messages(10)
    # item-cumulative tokens: batch seq = first item's token
    assert [(b.seq, b.items) for b in got] == \
        [(0, ["a", "b"]), (2, ["c"]), (3, ["d", "e", "f"])]
    # a repeat poll on the same receiver does not redeliver
    assert await r1.get_messages(10) == []
    await r1.ack(got[0])

    # "restart": a fresh receiver resumes from the durable cursor —
    # acked batches stay gone, unacked ones redeliver
    r2 = ad.create_receiver(q)
    redelivered = await r2.get_messages(10)
    assert [(b.seq, b.items) for b in redelivered] == \
        [(2, ["c"]), (3, ["d", "e", "f"])]
    await r2.ack(redelivered[0])
    await r2.ack(redelivered[1])

    # replay serves ACKED history from the durable log (rewind source)
    hist = await ad.replay(sid, 0)
    assert [(b.seq, b.items) for b in hist] == \
        [(0, ["a", "b"]), (2, ["c"]), (3, ["d", "e", "f"])]
    # from_seq filters batches wholly before the token
    hist = await ad.replay(sid, 3)
    assert [b.seq for b in hist] == [3]


@pytest.mark.parametrize("kind", ["file", "sqlite"])
async def test_durable_adapter_survives_reopen(kind, tmp_path):
    """The adapter object dying (process death) loses nothing: a new
    adapter over the same storage sees every unacked batch."""
    ad = _adapter(kind, tmp_path)
    sid = StreamId("p", "ns", "k2")
    q = ad.queue_of(sid)
    await ad.queue_message_batch(q, sid, [1, 2, 3])
    r = ad.create_receiver(q)
    got = await r.get_messages(10)
    await r.ack(got[0])
    await ad.queue_message_batch(q, sid, [4])
    if kind == "sqlite":
        ad.close()

    ad2 = _adapter(kind, tmp_path)
    r2 = ad2.create_receiver(q)
    got2 = await r2.get_messages(10)
    assert [b.items for b in got2] == [[4]]
    assert [b.items for b in await ad2.replay(sid, 0)] == [[1, 2, 3]]


async def test_file_adapter_recovers_from_torn_tail(tmp_path):
    """A crashed writer's partial trailing line must not poison the queue:
    the next produce truncates the torn tail and appends a parseable
    record; no acknowledged batch is lost."""
    ad = FileQueueAdapter(str(tmp_path / "queues"), n_queues=1)
    sid = StreamId("p", "ns", "k")
    await ad.queue_message_batch(0, sid, ["a", "b"])
    # simulate a crash mid-append: a torn, unterminated JSON fragment
    with open(ad._log(0), "a", encoding="utf-8") as f:
        f.write('{"sid": "AAAA", "b": "BB')
    await ad.queue_message_batch(0, sid, ["c"])
    r = ad.create_receiver(0)
    got = await r.get_messages(10)
    assert [(b.seq, b.items) for b in got] == [(0, ["a", "b"]), (2, ["c"])]


async def test_file_adapter_compaction_bounds_log(tmp_path):
    """The file log is bounded, not append-forever: once enough acks
    accumulate, compaction keeps unacked batches plus the newest
    `retention` acked ones, and a watermark record carries the token
    sequence over the dropped history (new produces keep their seq)."""
    ad = FileQueueAdapter(str(tmp_path / "queues"), n_queues=1,
                          retention=3)
    sid = StreamId("p", "ns", "k")
    for i in range(70):  # ack threshold is max(retention, 64)
        await ad.queue_message_batch(0, sid, [i])
    r = ad.create_receiver(0)
    for b in await r.get_messages(100):
        await r.ack(b)
    # the trigger-driven bound: retention + acks-since-last-compact,
    # never the full 70-batch history
    rows = ad._read_log(0)
    assert len(rows) < 70 and len(rows) <= 3 + 64, len(rows)
    # an explicit compact (what the next trigger does) reaches the exact
    # retention bound, keeping the NEWEST acked batches
    with ad._lock:
        ad._compact_locked(0)
    rows = ad._read_log(0)
    assert len(rows) == 3, rows
    hist = await ad.replay(sid, 0)
    assert [b.items for b in hist] == [[67], [68], [69]]
    # token continuity across the compaction: next produce continues
    await ad.queue_message_batch(0, sid, ["new"])
    got = await ad.create_receiver(0).get_messages(10)
    assert [(b.seq, b.items) for b in got] == [(70, ["new"])]
    # and a fresh adapter over the same directory agrees
    ad2 = FileQueueAdapter(str(tmp_path / "queues"), n_queues=1,
                           retention=3)
    got2 = await ad2.create_receiver(0).get_messages(10)
    assert [(b.seq, b.items) for b in got2] == [(70, ["new"])]


async def test_file_adapter_retention_zero_keeps_no_history(tmp_path):
    """retention=0 means NO acked history (matching the sqlite backend's
    LIMIT 0), not keep-everything (the [-0:] slice trap)."""
    ad = FileQueueAdapter(str(tmp_path / "queues"), n_queues=1,
                          retention=0)
    sid = StreamId("p", "ns", "k")
    for i in range(5):
        await ad.queue_message_batch(0, sid, [i])
    r = ad.create_receiver(0)
    for b in await r.get_messages(10):
        await r.ack(b)
    with ad._lock:
        ad._compact_locked(0)
    assert ad._read_log(0) == []
    assert await ad.replay(sid, 0) == []
    # token continuity still holds through the watermark
    await ad.queue_message_batch(0, sid, ["next"])
    got = await ad.create_receiver(0).get_messages(10)
    assert [(b.seq, b.items) for b in got] == [(5, ["next"])]


async def test_sqlite_retention_bounds_acked_history(tmp_path):
    ad = SqliteQueueAdapter(str(tmp_path / "q.db"), n_queues=1, retention=3)
    sid = StreamId("p", "n", "k")
    for i in range(6):
        await ad.queue_message_batch(0, sid, [i])
    r = ad.create_receiver(0)
    for b in await r.get_messages(10):
        await r.ack(b)
    hist = await ad.replay(sid, 0)
    assert [b.items for b in hist] == [[3], [4], [5]]  # newest 3 retained


# ---------------------------------------------------------------------------
# End-to-end through the pulling machinery
# ---------------------------------------------------------------------------

class ConsumerGrain(Grain):
    async def join(self, ns, key, from_token=None):
        stream = self.get_stream_provider("dq").get_stream(ns, key)
        await stream.subscribe(self.on_event, from_token=from_token)

    async def on_event(self, item, token):
        RECEIVED.setdefault(self.primary_key, []).append((token, item))


class ProducerGrain(Grain):
    async def publish(self, ns, key, items):
        stream = self.get_stream_provider("dq").get_stream(ns, key)
        await stream.on_next_batch(items)


async def _cluster(n, adapter, with_membership=False, cache_capacity=256):
    fabric = InProcFabric()
    storage = MemoryStorage()
    mbr = InMemoryMembershipTable()
    silos = []
    for i in range(n):
        b = (SiloBuilder().with_name(f"dq{i}").with_fabric(fabric)
             .add_grains(ConsumerGrain, ProducerGrain)
             .with_storage("Default", storage)
             .with_config(membership_probe_period=0.1,
                          membership_probe_timeout=0.15,
                          membership_missed_probes_limit=2,
                          membership_refresh_period=0.3,
                          response_timeout=2.0))
        add_persistent_streams(b, "dq", adapter, pull_period=0.05,
                               cache_capacity=cache_capacity,
                               rebalance_period=0.5)
        silo = b.build()
        if with_membership:
            join_cluster(silo, mbr)
        await silo.start()
        silos.append(silo)
    client = await ClusterClient(fabric).connect()
    return silos, client


async def _stop(silos, client):
    await client.close_async()
    for s in silos:
        if s.status not in ("Stopped", "Dead"):
            await s.stop()


async def _wait_count(key, count, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(RECEIVED.get(key, [])) >= count:
            return RECEIVED[key]
        await asyncio.sleep(0.03)
    raise AssertionError(
        f"{key}: got {len(RECEIVED.get(key, []))}, wanted {count}")


@pytest.mark.parametrize("kind", ["file", "sqlite"])
async def test_durable_stream_end_to_end(kind, tmp_path):
    RECEIVED.clear()
    silos, client = await _cluster(1, _adapter(kind, tmp_path))
    try:
        await client.get_grain(ConsumerGrain, 1).join("gps", "car")
        await client.get_grain(ProducerGrain, 1).publish(
            "gps", "car", list(range(5)))
        got = await _wait_count(1, 5)
        assert [i for _, i in got] == [0, 1, 2, 3, 4]
    finally:
        await _stop(silos, client)


async def test_silo_kill_mid_stream_loses_nothing(tmp_path):
    """Kill the queue-owning silo with undelivered+unacked events in
    flight: the surviving silo's balancer takes the queue over and its
    fresh receiver resumes from the durable ack cursor — every produced
    event is eventually delivered (at-least-once; dedup by token)."""
    RECEIVED.clear()
    adapter = SqliteQueueAdapter(str(tmp_path / "q.db"), n_queues=2)
    silos, client = await _cluster(3, adapter, with_membership=True)
    try:
        await client.get_grain(ConsumerGrain, 9).join("gps", "bus")
        prod = client.get_grain(ProducerGrain, 1)
        await prod.publish("gps", "bus", list(range(10)))
        await _wait_count(9, 10)

        # find and kill the silo whose agent owns the stream's queue
        sid = StreamId("dq", "gps", "bus")
        q = adapter.queue_of(sid)
        owner = next(s for s in silos
                     if q in s.stream_providers["dq"].manager.agents)
        # produce a second wave and kill the owner immediately — some of
        # these are pulled-but-unacked or not yet pulled at kill time
        await prod.publish("gps", "bus", list(range(10, 30)))
        await owner.stop(graceful=False)

        got = await _wait_count(9, 30, timeout=20.0)
        items = {i for _, i in got}
        assert items == set(range(30)), sorted(set(range(30)) - items)
        # tokens are unique per item: dedup-by-token recovers exactly-once
        toks = [t for t, _ in got]
        uniq = {}
        for t, i in got:
            uniq.setdefault(t, i)
        assert sorted(uniq.values()) == list(range(30))
        assert len(toks) >= 30  # redelivery (duplicates) is allowed
    finally:
        await _stop(silos, client)


async def test_rewind_beyond_cache_replays_durable_history(tmp_path):
    """A subscription rewound to token 0 after the cache window has moved
    on replays acked batches from the durable log — beyond what the
    in-memory cache retains (the EventHub-offset retention replay)."""
    RECEIVED.clear()
    adapter = SqliteQueueAdapter(str(tmp_path / "q.db"), n_queues=1)
    silos, client = await _cluster(1, adapter, cache_capacity=4)
    try:
        await client.get_grain(ConsumerGrain, 1).join("gps", "t")
        prod = client.get_grain(ProducerGrain, 1)
        for i in range(40):  # 40 batches >> cache capacity 4
            await prod.publish("gps", "t", [i])
        await _wait_count(1, 40)
        # let eviction+ack drain the cache behind the consumer
        await asyncio.sleep(0.5)
        agent = silos[0].stream_providers["dq"].manager.agents[0]
        assert agent.cache.count < 40  # the cache window really moved on

        # a NEW consumer rewinds to the beginning
        await client.get_grain(ConsumerGrain, 2).join(
            "gps", "t", from_token=0)
        got = await _wait_count(2, 40, timeout=15.0)
        uniq = {}
        for t, i in got:
            uniq.setdefault(t, i)
        assert sorted(uniq.values()) == list(range(40))
    finally:
        await _stop(silos, client)


async def test_sqlite_token_continuity_after_full_drain(tmp_path):
    """ADVICE r4: retention can DELETE every row of a drained queue; the
    per-queue watermark must keep the next token sequence monotone so a
    restart never re-mints already-delivered tokens."""
    from orleans_tpu.streams import SqliteQueueAdapter
    from orleans_tpu.streams.core import StreamId

    path = str(tmp_path / "wm.db")
    a = SqliteQueueAdapter(path, n_queues=1, retention=0)  # keep nothing
    sid = StreamId("dq", "ns", "k")
    for i in range(3):
        await a.queue_message_batch(0, sid, [f"a{i}", f"b{i}"])
    recv = a.create_receiver(0)
    batches = await recv.get_messages(10)
    assert [b.seq for b in batches] == [0, 2, 4]
    for b in batches:
        await recv.ack(b)  # retention=0: every acked row is deleted
    a.close()

    # fresh adapter over the drained db: tokens must CONTINUE, not restart
    b2 = SqliteQueueAdapter(path, n_queues=1, retention=0)
    await b2.queue_message_batch(0, sid, ["post-drain"])
    got = await b2.create_receiver(0).get_messages(10)
    assert [x.seq for x in got] == [6], [x.seq for x in got]
    b2.close()


async def test_group_commit_flush_failure_fails_every_waiter(tmp_path):
    """A flush-group commit failure must reject every produce that rode
    the group — none may report durable success."""
    from orleans_tpu.streams import SqliteQueueAdapter
    from orleans_tpu.streams.core import StreamId

    a = SqliteQueueAdapter(str(tmp_path / "gc.db"), n_queues=1)
    sid = StreamId("dq", "ns", "k")
    a._db.close()  # storage dies before the group commits
    results = await asyncio.gather(
        *(a.queue_message_batch(0, sid, [i]) for i in range(8)),
        return_exceptions=True)
    assert all(isinstance(r, Exception) for r in results), results
