"""Device profiling hooks (jax.profiler trace capture + annotations +
slow-step accounting — SURVEY §5 tracing TPU equivalent)."""

import os

import pytest

import jax.numpy as jnp

from orleans_tpu.observability import Profiler, StatsRegistry, StepTimer, \
    annotate, traced


def test_trace_capture_writes_files(tmp_path):
    p = Profiler()
    with p.capture(str(tmp_path)):
        with annotate("test-span"):
            jnp.arange(128).sum().block_until_ready()
    assert p.active_dir is None
    dumped = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert dumped, "no trace files written"


def test_double_start_rejected(tmp_path):
    p = Profiler()
    p.start(str(tmp_path))
    try:
        with pytest.raises(RuntimeError, match="already active"):
            p.start(str(tmp_path))
    finally:
        p.stop()
    assert p.stop() is None  # idempotent


async def test_traced_is_coroutine_aware_and_preserves_metadata():
    """@traced on an async handler must await inside the annotation (the
    old wrapper returned the coroutine with the span already closed) and
    keep the function's metadata via functools.wraps."""
    import asyncio
    import inspect

    @traced("async-work")
    async def handler(x):
        """docstring survives"""
        await asyncio.sleep(0)
        return x * 2

    assert inspect.iscoroutinefunction(handler)
    assert handler.__name__ == "handler"
    assert handler.__doc__ == "docstring survives"
    assert await handler(3) == 6

    @traced("sync-work")
    def sync_handler(x):
        return x + 1

    assert sync_handler.__name__ == "sync_handler"
    assert sync_handler.__wrapped__(1) == 2  # functools.wraps marker
    assert sync_handler(1) == 2


def test_traced_decorator_and_step_timer():
    stats = StatsRegistry()
    timer = StepTimer(stats, "tick", warn_threshold=0.0)  # always slow

    @traced("work")
    def work(x):
        return x + 1

    with timer.step():
        assert work(1) == 2
    assert stats.get("tick.slow") == 1
    assert sum(stats.histogram("tick.seconds").counts) >= 1
