"""Test configuration.

* Forces CPU jax with an 8-device virtual mesh so multi-"silo" sharding tests
  run anywhere (the driver validates the real multi-chip path separately via
  __graft_entry__.dryrun_multichip).
* Minimal async-test support: any ``async def test_*`` runs under
  ``asyncio.run`` (no pytest-asyncio in the image).
"""

import os

# Must run before jax backends initialize. The image exports
# JAX_PLATFORMS=axon (the real TPU tunnel); tests pin CPU explicitly.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs with `-m 'not slow'`; register the marker so slow-marked
    # soaks (rebalance convergence, chaos) don't warn
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 fast suite")


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(fn(**kwargs))
        return True
    return None
