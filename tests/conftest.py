"""Test configuration: force CPU jax with an 8-device virtual mesh so
multi-"silo" sharding tests run anywhere (the driver validates the real
multi-chip path separately via __graft_entry__.dryrun_multichip)."""

import os

# Must run before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def anyio_backend():
    return "asyncio"
