"""Tests for grain services (ring-partitioned per-silo services), interface
versioning (compat-gated placement), and multi-cluster gossip + GSI."""

import asyncio
import time

import pytest

from orleans_tpu.core.ids import GrainId, GrainType
from orleans_tpu.membership import InMemoryMembershipTable, join_cluster
from orleans_tpu.multicluster import (
    GlobalSingleInstanceRegistrar,
    GsiState,
    InMemoryGossipChannel,
    MultiClusterOracle,
    add_multicluster,
)
from orleans_tpu.runtime import ClusterClient, Grain, InProcFabric, SiloBuilder
from orleans_tpu.services import GrainService, GrainServiceClient, add_grain_service
from orleans_tpu.storage import MemoryStorage
from orleans_tpu.versions import grain_version


# ---------------------------------------------------------------------------
# Grain services
# ---------------------------------------------------------------------------

class KvService(GrainService):
    """Toy partitioned service: per-silo kv shards routed by key."""

    def __init__(self, silo):
        super().__init__(silo)
        self.data = {}

    async def put(self, key, value):
        self.data[key] = value
        return self.silo.silo_address

    async def get_value(self, key):
        return self.data.get(key)


class ServiceUserGrain(Grain):
    """Grain using the service client (GrainServiceClient consumer)."""

    async def put_via_service(self, key, value):
        client = GrainServiceClient(self._activation.runtime, KvService)
        return str(await client.call(key, "put", key, value))


async def test_grain_service_partitions_by_key_and_reranges():
    fabric = InProcFabric()
    mbr = InMemoryMembershipTable()
    silos = []
    for i in range(3):
        b = (SiloBuilder().with_name(f"gs{i}").with_fabric(fabric)
             .add_grains(ServiceUserGrain)
             .with_storage("Default", MemoryStorage())
             .with_config(membership_probe_period=0.1,
                          membership_probe_timeout=0.15,
                          membership_missed_probes_limit=2,
                          membership_refresh_period=0.3,
                          response_timeout=2.0))
        add_grain_service(b, KvService)
        silo = b.build()
        join_cluster(silo, mbr)
        await silo.start()
        silos.append(silo)
    client = await ClusterClient(fabric).connect()
    try:
        # routing is deterministic: same key → same owner from any silo
        grain = client.get_grain(ServiceUserGrain, 1)
        owners = {}
        for k in range(20):
            owners[k] = await grain.put_via_service(f"k{k}", k)
        assert len(set(owners.values())) > 1  # keys spread across silos
        svc_client = GrainServiceClient(silos[0], KvService)
        for k in range(20):
            assert await svc_client.call(f"k{k}", "get_value", f"k{k}") == k
        # ranges shrink/grow with membership: kill a silo, routing re-ranges
        victim = silos[2]
        await victim.stop(graceful=False)
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and not all(
                victim.silo_address in s.membership.dead for s in silos[:2]):
            await asyncio.sleep(0.05)
        for k in range(20):
            # every key routable again (data on the dead shard is gone —
            # services are caches/partitions, not replicated stores)
            await svc_client.call(f"k{k}", "put", f"k{k}", k * 2)
            assert await svc_client.call(f"k{k}", "get_value", f"k{k}") == k * 2
    finally:
        await client.close_async()
        for s in silos:
            if s.status not in ("Stopped", "Dead"):
                await s.stop()


# ---------------------------------------------------------------------------
# Interface versioning
# ---------------------------------------------------------------------------

@grain_version(1)
class ApiGrainV1(Grain):
    async def ping(self):
        return ("v1", self.runtime_identity)


@grain_version(2)
class ApiGrainV2(Grain):
    async def ping(self):
        return ("v2", self.runtime_identity)


# Same interface name on both silos, different versions: simulate a rolling
# upgrade by registering a v1 class on silo A and a v2 class on silo B under
# one name.
ApiGrainV2.__name__ = "ApiGrain"
ApiGrainV1.__name__ = "ApiGrain"


async def test_version_gated_placement_backward_compat():
    fabric = InProcFabric()
    storage = MemoryStorage()
    old_silo = (SiloBuilder().with_name("old").with_fabric(fabric)
                .add_grains(ApiGrainV1).with_storage("Default", storage)
                .build())
    await old_silo.start()
    new_silo = (SiloBuilder().with_name("new").with_fabric(fabric)
                .add_grains(ApiGrainV2).with_storage("Default", storage)
                .build())
    await new_silo.start()
    try:
        # a caller compiled against v2 must land on the v2 silo, every time
        for k in range(10):
            ref = new_silo.grain_factory.get_grain(ApiGrainV2, k)
            version, where = await ref.ping()
            assert version == "v2", f"key {k} placed on {where}"
        # a v1 caller may land anywhere (backward compat: v2 serves v1)
        versions = set()
        for k in range(20, 40):
            ref = old_silo.grain_factory.get_grain(ApiGrainV1, k)
            v, _ = await ref.ping()
            versions.add(v)
        assert "v1" in versions or "v2" in versions  # both acceptable
    finally:
        await new_silo.stop()
        await old_silo.stop()


async def test_strict_compat_rejects_mismatch():
    fabric = InProcFabric()
    storage = MemoryStorage()
    old_silo = (SiloBuilder().with_name("old2").with_fabric(fabric)
                .add_grains(ApiGrainV1).with_storage("Default", storage)
                .build())
    await old_silo.start()
    old_silo.locator.versions.set_strategy(compat="strict")
    try:
        ref = old_silo.grain_factory.get_grain(ApiGrainV2, 99)
        with pytest.raises(Exception, match="compatible"):
            await ref.ping()
    finally:
        await old_silo.stop()


# ---------------------------------------------------------------------------
# Multi-cluster gossip + GSI
# ---------------------------------------------------------------------------

async def make_cluster(name, channel):
    fabric = InProcFabric()
    b = (SiloBuilder().with_name(name).with_fabric(fabric)
         .with_storage("Default", MemoryStorage()))
    add_multicluster(b, name, [channel], gossip_period=0.1)
    silo = b.build()
    await silo.start()
    return silo


async def test_gossip_exchanges_gateways_between_clusters():
    channel = InMemoryGossipChannel()
    a = await make_cluster("clusterA", channel)
    b = await make_cluster("clusterB", channel)
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if (set(a.multicluster.known_clusters()) >=
                    {"clusterA", "clusterB"} and
                    set(b.multicluster.known_clusters()) >=
                    {"clusterA", "clusterB"}):
                break
            await asyncio.sleep(0.05)
        assert a.multicluster.gateways_of("clusterB") == [b.silo_address]
        assert b.multicluster.gateways_of("clusterA") == [a.silo_address]
    finally:
        await a.stop()
        await b.stop()


async def test_gsi_ownership_cached_and_race_resolution():
    registrars = {}

    async def peer_query(cluster_id, grain_id):
        return registrars[cluster_id].status_of(grain_id)

    for cid in ("alpha", "beta"):
        registrars[cid] = GlobalSingleInstanceRegistrar(
            cid, lambda: ["alpha", "beta"], peer_query)

    gid = GrainId.for_grain(GrainType.of("GeoGrain"), 1)
    # alpha registers first: owned
    e1 = await registrars["alpha"].register(gid)
    assert e1.state == GsiState.OWNED and e1.owner_cluster == "alpha"
    # beta then finds alpha's ownership: cached
    e2 = await registrars["beta"].register(gid)
    assert e2.state == GsiState.CACHED and e2.owner_cluster == "alpha"

    # simultaneous race on a fresh grain: lexicographic winner owns
    gid2 = GrainId.for_grain(GrainType.of("GeoGrain"), 2)
    r_alpha, r_beta = await asyncio.gather(
        registrars["alpha"].register(gid2),
        registrars["beta"].register(gid2))
    states = {(r_alpha.state, r_alpha.owner_cluster),
              (r_beta.state, r_beta.owner_cluster)}
    # alpha < beta lexicographically: beta must not claim ownership
    assert r_beta.state in (GsiState.RACE_LOSER, GsiState.CACHED)
    assert r_alpha.state in (GsiState.OWNED, GsiState.DOUBTFUL,
                             GsiState.REQUESTED_OWNERSHIP)
    # maintainer pass converges the loser to cached-at-winner
    await registrars["alpha"].retry_doubtful()
    await registrars["beta"].retry_doubtful()
    assert registrars["beta"].status_of(gid2)[1] in ("alpha", None) or \
        registrars["beta"].entries[gid2].state == GsiState.CACHED


async def test_gsi_doubtful_when_peer_unreachable():
    async def peer_query(cluster_id, grain_id):
        raise ConnectionError("DCN down")

    reg = GlobalSingleInstanceRegistrar(
        "alpha", lambda: ["alpha", "beta"], peer_query)
    gid = GrainId.for_grain(GrainType.of("GeoGrain"), 3)
    e = await reg.register(gid)
    assert e.state == GsiState.DOUBTFUL  # owned-but-unconfirmed, will retry
