"""SLO engine (ISSUE 12): declarative specs + multi-window burn-rate
detection from interval-diffed snapshots, the wired breach path (flight
recorder + tail-trace force-retention + cluster rollup), the
per-(class, method) call-site table, the ``Histogram.delta`` primitive,
the Perfetto slow-callback flame row, and the traffic-shape gauntlet
(flash-crowd QoS invariant, diurnal negative control, churn storm)."""

import asyncio
from types import SimpleNamespace

import pytest

from orleans_tpu.config import SloOptions
from orleans_tpu.core.errors import ConfigurationError
from orleans_tpu.management import ManagementGrain
from orleans_tpu.observability.slo import SloMonitor, SloSpec
from orleans_tpu.observability.stats import (SLO_STATS, CallSiteStats,
                                             Histogram, StatsRegistry)
from orleans_tpu.runtime import Grain
from orleans_tpu.testing import TestClusterBuilder


# ---------------------------------------------------------------------------
# Histogram.delta — the interval-diff primitive
# ---------------------------------------------------------------------------

def test_histogram_delta_basic():
    h = Histogram()
    for v in (0.001, 0.01, 0.2):
        h.observe(v)
    snap = h.summary()
    h.observe(0.3)
    h.observe(0.0005)
    d = h.delta(snap)
    assert d.total == 2
    assert abs(d.sum - 0.3005) < 1e-9
    # the delta holds ONLY the new observations, in their buckets
    assert d.good_below(0.001) == 1      # the 0.0005 one
    assert d.good_below(0.25) == 1       # 0.3 is above
    # the cumulative histogram is untouched
    assert h.total == 5


def test_histogram_delta_none_snapshot_is_copy():
    h = Histogram()
    h.observe(0.05)
    d = h.delta(None)
    assert d.total == 1 and d.counts == h.counts
    d.observe(0.05)
    assert h.total == 1  # a copy, not a view


def test_histogram_delta_mismatched_bounds_is_safe():
    """A snapshot taken with different bucket bounds folds onto the live
    histogram's bounds (the PR-8 widening rule) before subtracting —
    counts never go negative and never subtract positionally against
    the wrong bucket."""
    h = Histogram()                      # default latency bounds
    for v in (0.001, 0.01, 0.2, 2.0):
        h.observe(v)
    prev = Histogram([0.005, 0.1, float("inf")])  # coarse foreign bounds
    prev.observe(0.001)
    prev.observe(0.01)
    d = h.delta(prev.summary())
    assert all(c >= 0 for c in d.counts)
    assert d.total == sum(d.counts)
    # conservative: at most the cumulative count survives
    assert d.total <= h.total


def test_histogram_good_below_is_bucket_conservative():
    h = Histogram([0.01, 0.1, 1.0, float("inf")])
    h.observe(0.005)   # bucket <=0.01
    h.observe(0.05)    # bucket <=0.1
    h.observe(5.0)     # +Inf bucket
    assert h.good_below(0.01) == 1
    # threshold INSIDE a bucket excludes that bucket (conservative)
    assert h.good_below(0.05) == 1
    assert h.good_below(0.1) == 2
    # the +Inf bucket can never prove an observation under any finite
    # threshold — 5.0 landed there, so it stays bad (conservative)
    assert h.good_below(100.0) == 2


# ---------------------------------------------------------------------------
# CallSiteStats — the breach drill-down table
# ---------------------------------------------------------------------------

def test_callsite_stats_topk_bounded_merge():
    cs = CallSiteStats(cap=3)
    for i in range(10):
        cs.note("A", "slow", 0.05)
    cs.note("A", "fast", 0.001)
    cs.note("B", "err", 0.01, error=True)
    cs.note("C", "dropped", 1.0)  # 4th site: over the cap
    assert cs.overflow == 1
    assert len(cs.sites) == 3
    top = cs.top(2, by="sum")
    assert top[0]["site"] == "A.slow" and top[0]["count"] == 10
    assert cs.top(1, by="errors")[0]["site"] == "B.err"
    # merge: counts/errors/seconds sum, max takes max
    merged = CallSiteStats.merge([cs.snapshot(), cs.snapshot()])
    assert merged["sites"]["A.slow"][0] == 20
    assert merged["sites"]["B.err"][1] == 2
    assert merged["overflow"] == 2
    # snapshot(k) bounds the payload to the top-k by seconds
    assert len(cs.snapshot(1)["sites"]) == 1


# ---------------------------------------------------------------------------
# Burn-rate math: multi-window confirm + recovery (deterministic clock)
# ---------------------------------------------------------------------------

def _stub_silo(**cfg_kw) -> SimpleNamespace:
    """The minimal surface SloMonitor touches: stats registry + config +
    the breach-path consumers (absent here — the unit tests assert the
    math; the e2e test below asserts the wiring)."""
    from orleans_tpu.runtime.silo import SiloConfig
    cfg = SiloConfig(name="stub", **cfg_kw)
    return SimpleNamespace(stats=StatsRegistry(), config=cfg,
                           tracer=None, loop_prof=None, call_sites=None)


def test_multi_window_burn_confirm_and_recovery():
    """The Google-SRE shape: a fast-window spike alone does not page —
    the slow window must confirm; sustained burn breaches; cooling the
    fast window recovers."""
    silo = _stub_silo()
    spec = SloSpec("lat", kind="latency", target=0.9, threshold=0.01,
                   source="x.seconds", fast_window=2.0, slow_window=10.0,
                   burn_threshold=2.0, min_events=5)
    mon = SloMonitor(silo, specs=[spec], period=1.0)
    h = silo.stats.histogram("x.seconds")
    t = 1000.0

    # 8 ticks of healthy traffic fill the slow window with good events
    for _ in range(8):
        for _ in range(20):
            h.observe(0.001)
        assert mon.evaluate_once(t) == []
        t += 1.0
    obj = mon.objectives["lat"]
    assert obj.burn_fast == 0.0 and not obj.breached

    # one tick of pure badness: fast window burns 10x, slow window is
    # still diluted by 160 good events -> NO breach (no single-interval
    # paging)
    for _ in range(20):
        h.observe(0.5)
    assert mon.evaluate_once(t) == []
    assert obj.burn_fast >= 2.0, obj.burn_fast
    assert obj.burn_slow < 2.0
    assert not obj.breached
    t += 1.0

    # sustained badness: the slow window confirms -> breach (and the
    # slo.* counters/gauges land)
    newly = []
    for _ in range(12):
        for _ in range(20):
            h.observe(0.5)
        newly += mon.evaluate_once(t)
        t += 1.0
    assert newly == ["lat"]
    assert obj.breached and obj.breaches == 1
    assert silo.stats.get(SLO_STATS["breaches"]) == 1
    assert silo.stats.gauge(SLO_STATS["breached"] % "lat") == 1.0
    assert obj.budget_burned > 1.0  # over budget for the observed volume

    # recovery: good traffic cools the fast window below the threshold
    for _ in range(4):
        for _ in range(50):
            h.observe(0.001)
        mon.evaluate_once(t)
        t += 1.0
    assert not obj.breached
    assert obj.breaches == 1  # the episode is history, not forgotten
    assert silo.stats.gauge(SLO_STATS["breached"] % "lat") == 0.0


def test_error_and_shed_rate_objectives_from_counters():
    silo = _stub_silo()
    specs = [
        SloSpec("err", kind="error_rate", target=0.9,
                bad_source="turns.errors", total_source="turns.total",
                fast_window=2.0, slow_window=6.0, burn_threshold=2.0,
                min_events=4),
        SloSpec("shed", kind="shed_rate", target=0.9,
                bad_source="gw.shed", total_source="turns.total",
                fast_window=2.0, slow_window=6.0, burn_threshold=2.0,
                min_events=4),
    ]
    mon = SloMonitor(silo, specs=specs, period=1.0)
    t = 0.0
    # healthy: 100 turns, no errors/sheds
    silo.stats.increment("turns.total", 100)
    mon.evaluate_once(t)
    err, shed = mon.objectives["err"], mon.objectives["shed"]
    assert err.burn_fast == 0.0
    # sustained 50%-error / 50%-shed ticks (interval semantics: each
    # tick sees only the counter DELTAS): the fast window burns first,
    # the breach waits until the healthy baseline ages out of the slow
    # window — the multi-window confirm on the counter kinds
    newly: list[str] = []
    immediate = None
    for _ in range(8):
        t += 1.0
        silo.stats.increment("turns.total", 10)
        silo.stats.increment("turns.errors", 5)
        silo.stats.increment("gw.shed", 10)
        got = mon.evaluate_once(t)
        if immediate is None:
            immediate = bool(got)  # first bad tick must NOT page alone
        newly += got
    assert immediate is False
    assert "err" in newly and "shed" in newly
    assert err.breached and shed.breached
    assert err.burn_fast >= 2.0 and shed.burn_fast >= 2.0


def test_default_specs_without_metrics_is_probe_only():
    """With metrics disabled the latency histogram and turn/message
    totals never observe — but turn errors and gateway sheds still
    count, so a ratio objective would read every bad event as a
    100%-bad interval and fabricate a breach. default_specs must
    install ONLY the probe-RTT objective then."""
    from orleans_tpu.observability.slo import default_specs
    from orleans_tpu.runtime.silo import SiloConfig
    assert [s.name for s in default_specs(SiloConfig())] == ["probe_rtt"]
    names = [s.name for s in default_specs(SiloConfig(metrics_enabled=True))]
    assert names == ["app_latency", "probe_rtt", "turn_errors",
                     "shed_rate", "stream_latency"]


def test_slo_spec_and_options_validation():
    with pytest.raises(ConfigurationError):
        SloSpec("x", kind="nonsense").validate()
    with pytest.raises(ConfigurationError):
        SloSpec("x", target=1.0).validate()  # zero budget
    with pytest.raises(ConfigurationError):
        SloSpec("x", fast_window=10.0, slow_window=5.0).validate()
    with pytest.raises(ConfigurationError):
        SloSpec("x", kind="latency", source=None).validate()
    with pytest.raises(ConfigurationError):
        SloOptions(fast_window=300.0, slow_window=60.0).validate()
    with pytest.raises(ConfigurationError):
        SloOptions(error_target=0.0).validate()
    SloOptions().validate()


# ---------------------------------------------------------------------------
# End-to-end breach path: flight recorder + force-retention + rollup
# ---------------------------------------------------------------------------

class SlowGrain(Grain):
    async def work(self, x: int) -> int:
        await asyncio.sleep(0.02)
        return x


class FrontGrain(Grain):
    """Calls SlowGrain from inside the silo, so the nested call roots a
    SILO-side trace (the test client stays untraced) — the in-flight
    traces a breach must force-retain."""

    async def go(self, i: int) -> int:
        ref = self.get_grain(SlowGrain, i % 2)
        return await ref.work(i)


async def test_breach_fires_flight_recorder_retention_and_rollup():
    """The acceptance path end-to-end: saturating two slow grains makes
    ingest queue-wait torch a tight latency budget; the breach must (a)
    mark the objective breached with slo.* counters, (b) snapshot the
    flight recorder with reason ``slo_breach`` carrying the objective,
    (c) force-retain the in-flight tail traces (which would ALL be
    dropped under the sky-high slow threshold otherwise), and (d) roll
    up cluster-wide through ManagementGrain.get_cluster_slo with
    worst-burn-wins + call-site drill-down."""
    b = (TestClusterBuilder(n_silos=2)
         .add_grains(SlowGrain, FrontGrain)
         .with_slo(latency_threshold=0.005, latency_target=0.9)
         .with_profiling(window=0.1, trigger_interval=0.05)
         # tail mode with an unreachable slow threshold: NOTHING retains
         # on latency/error — only the breach's force-retention keeps
         .with_tracing(tail=True, slow_threshold=999.0, client=False)
         .with_config(hot_lane_enabled=False))
    async with b.build() as cluster:
        fronts = [cluster.grain(FrontGrain, k) for k in range(8)]
        await asyncio.gather(*(g.go(0) for g in fronts))  # activate

        stop = asyncio.Event()

        async def hammer(wid: int) -> None:
            i = wid
            while not stop.is_set():
                await fronts[i % len(fronts)].go(i)
                i += 1

        tasks = [asyncio.ensure_future(hammer(w)) for w in range(16)]
        try:
            def breached() -> bool:
                return any(s.slo is not None and s.slo.status()["breaches"]
                           for s in cluster.silos)
            await cluster.wait_until(breached, timeout=15.0,
                                     msg="SLO breach under slow-grain load")
            # keep traffic in the air a moment so pending traces exist
            # at the breach instant (force-retention's subjects)
            await asyncio.sleep(0.2)
        finally:
            stop.set()
            await asyncio.gather(*tasks, return_exceptions=True)

        # (a) objective state + counters
        hot = next(s for s in cluster.silos
                   if s.slo.status()["breaches"] > 0)
        st = hot.slo.status()
        assert st["objectives"]["app_latency"]["breaches"] >= 1
        assert hot.stats.get(SLO_STATS["breaches"]) >= 1
        assert hot.stats.get(SLO_STATS["breach"] % "app_latency") >= 1

        # (b) flight recorder snapshot with the breached objective
        # (TestCluster silos share one loop -> one profiler)
        snaps = [s for s in hot.loop_prof.snapshots
                 if s["reason"] == "slo_breach"]
        assert snaps, "no slo_breach flight-recorder snapshot"
        assert snaps[0]["attrs"]["objective"] in ("app_latency",
                                                  "turn_errors",
                                                  "shed_rate", "probe_rtt")
        assert snaps[0]["attrs"]["burn_fast"] >= 2.0

        # (c) force-retention: with slow_threshold=999 and zero errors,
        # ONLY forced traces survive the tail decision
        await cluster.drain_traces()
        ret = cluster.retention_stats()
        assert ret.get("kept", 0) >= 1, ret

        # (d) cluster rollup: worst-burn-wins + per-silo drill-down
        mg = cluster.grain(ManagementGrain, 0)
        roll = await mg.get_cluster_slo()
        assert roll["breaches"] >= 1
        app = roll["objectives"]["app_latency"]
        assert app["breaches"] >= 1 and app["worst_silo"]
        assert roll["per_silo"]  # the drill-down payloads ride along
        some = next(iter(roll["per_silo"].values()))
        assert "call_sites" in some  # breach -> hot grain methods
        sites = await mg.get_cluster_call_sites(5)
        assert any(s["site"] == "SlowGrain.work" for s in sites)
        assert any(s["site"] == "FrontGrain.go" for s in sites)


async def test_slo_disabled_costs_and_serves_nothing():
    async with TestClusterBuilder(n_silos=1).build() as cluster:
        silo = cluster.silos[0]
        assert silo.slo is None and silo.call_sites is None
        ctl = await silo.silo_control.ctl_slo()
        assert ctl == {}
        assert await silo.silo_control.ctl_call_sites() == {}
        mg = cluster.grain(ManagementGrain, 0)
        roll = await mg.get_cluster_slo()
        assert roll["objectives"] == {} and not roll["breached"]


# ---------------------------------------------------------------------------
# Perfetto flame row: top-K slow-callback records as spans
# ---------------------------------------------------------------------------

def test_chrome_trace_promotes_slow_callbacks_to_spans():
    from orleans_tpu.observability.export import chrome_trace_events
    windows = [{
        "ts": 100.5, "wall_s": 0.5,
        "seconds": {"turns": 0.3, "idle": 0.2},
        "shares": {"turns": 0.6, "idle": 0.4},
        "top": [
            {"seconds": 0.2, "category": "turns", "label": "Echo.ping"},
            {"seconds": 0.05, "category": "pump", "label": "recv"},
        ],
    }]
    events = chrome_trace_events([], loop_profiles={"silo0": windows})
    rows = [e for e in events if e.get("ph") == "M"
            and e.get("name") == "thread_name"]
    assert any(e["args"]["name"] == "slow callbacks" for e in rows)
    spans = [e for e in events if e.get("ph") == "X"]
    assert {s["name"] for s in spans} == {"Echo.ping", "recv"}
    ping = next(s for s in spans if s["name"] == "Echo.ping")
    assert ping["cat"] == "turns"
    assert abs(ping["dur"] - 0.2e6) < 1.0  # microseconds, exact duration
    # records lie INSIDE their window beside the counter track
    counters = [e for e in events if e.get("ph") == "C"]
    assert counters and counters[0]["args"]["turns"] == 0.6
    win_start_us = 0.0  # earliest ts on the zeroed timeline
    assert ping["ts"] >= win_start_us
    assert ping["ts"] + ping["dur"] <= 0.5e6 + 1.0


def test_chrome_trace_flame_rows_never_overlap_across_windows():
    """A window whose top-K durations sum past its end SPILLS past the
    boundary, and the next window's records start after the spill —
    overlapping same-tid complete events would render as bogus
    nesting."""
    from orleans_tpu.observability.export import chrome_trace_events
    windows = [
        {"ts": 100.5, "wall_s": 0.5, "shares": {"turns": 1.0},
         "top": [{"seconds": 0.4, "category": "turns", "label": "a"},
                 {"seconds": 0.4, "category": "turns", "label": "b"}]},
        {"ts": 101.0, "wall_s": 0.5, "shares": {"turns": 1.0},
         "top": [{"seconds": 0.1, "category": "turns", "label": "c"}]},
    ]
    events = chrome_trace_events([], loop_profiles={"s": windows})
    spans = sorted((e for e in events if e.get("ph") == "X"),
                   key=lambda e: e["ts"])
    assert [s["name"] for s in spans] == ["a", "b", "c"]
    for prev, nxt in zip(spans, spans[1:]):
        assert nxt["ts"] >= prev["ts"] + prev["dur"] - 1e-6


def test_chrome_trace_places_offset_records_exactly():
    """ISSUE 13 satellite: records carrying a within-window start
    offset (stamped by the profiler hot path) render at window_start +
    offset — exact placement, not the end-to-end cursor layout — and
    exact records never overlap (callbacks are sequential)."""
    from orleans_tpu.observability.export import chrome_trace_events
    windows = [
        {"ts": 100.5, "wall_s": 0.5, "shares": {"turns": 1.0},
         "top": [
             {"seconds": 0.05, "category": "turns", "label": "a",
              "offset": 0.30},
             {"seconds": 0.02, "category": "pump", "label": "b",
              "offset": 0.10},
         ]},
    ]
    events = chrome_trace_events([], loop_profiles={"s": windows})
    spans = {e["name"]: e for e in events if e.get("ph") == "X"}
    # window start = ts - wall = 100.0 = t0 (zeroed timeline)
    assert abs(spans["a"]["ts"] - 0.30e6) < 1.0
    assert abs(spans["b"]["ts"] - 0.10e6) < 1.0
    # exact records do not overlap even though the list is
    # duration-sorted, not time-sorted
    ordered = sorted(spans.values(), key=lambda e: e["ts"])
    for prev, nxt in zip(ordered, ordered[1:]):
        assert nxt["ts"] >= prev["ts"] + prev["dur"] - 1e-6


# ---------------------------------------------------------------------------
# Gauntlet: flash-crowd QoS invariant + negative controls
# ---------------------------------------------------------------------------

def _check_verdicts(verdicts: dict) -> None:
    assert verdicts, "no SLO verdicts emitted"
    for v in verdicts.values():
        assert {"objective", "kind", "met", "breached", "burn_fast",
                "burn_slow", "budget_burned", "events",
                "time_to_detect"} <= set(v)


async def test_gauntlet_flash_crowd_breaches_app_slo_but_not_qos():
    """The acceptance scenario (and the PR-10/11 QoS regression guard):
    a 10x step on a 2-silo membership cluster must breach the app SLO
    (with a measured time-to-detect) and shed application traffic,
    while the PING lane stays clean — probe RTT p99 bounded by the
    probe timeout, ZERO false suspicion votes, membership stable."""
    from benchmarks import gauntlet
    r = await gauntlet.flash_crowd(seconds=2.5, short=True)
    e = r["extra"]
    _check_verdicts(e["verdicts"])
    # the app-facing SLO saw the crowd...
    assert e["app_slo_breached"], e["verdicts"]
    breached = [v for v in e["verdicts"].values() if v["breached"]]
    assert breached
    ttds = [v["time_to_detect"] for v in breached
            if v["time_to_detect"] is not None]
    assert ttds and min(ttds) <= e["seconds"], e["verdicts"]
    # ...the overload was real (gateway actually shed client ingress)...
    assert e["gateway_sheds"] > 0
    # ...and the QoS lane did not: probes never sat behind the crowd.
    # Gated on the probe SLI fraction (>= 90% of probes provably under
    # the timeout) — a bucket-quantized p99 over a few dozen samples is
    # one slow probe away from a false failure, while a real QoS break
    # drags MOST probes over the bound
    assert e["false_suspicions"] == 0
    assert e["membership_stable"]
    assert e["probe_rtt_fast_fraction"] is not None
    assert e["probe_rtt_fast_fraction"] >= 0.9, \
        f"only {e['probe_rtt_fast_fraction']:.2f} of probes under the " \
        f"{e['probe_rtt_bound_s']}s bound under flash-crowd load " \
        f"(p99 {e['probe_rtt_p99_s']})"
    assert e["qos_invariant_held"]
    # the breach left flight-recorder evidence
    assert e["breach_snapshots"] >= 1


async def test_gauntlet_diurnal_is_breach_free():
    """Negative control: an ordinary (compressed) diurnal ramp must NOT
    page. The noise-tolerant threshold keeps a loaded shared core from
    flaking the control — the scenario still swings load 3x."""
    from benchmarks import gauntlet
    r = await gauntlet.diurnal(seconds=1.2, short=True, threshold=0.15)
    e = r["extra"]
    _check_verdicts(e["verdicts"])
    assert e["all_met"], e["verdicts"]
    assert e["calls"] > 0


async def test_gauntlet_churn_storm_drops_nothing():
    """Churn storm: clients connecting/calling/disconnecting in a loop
    beside base load — zero failed calls, objectives met (lenient
    threshold for suite noise), and real churn actually happened."""
    from benchmarks import gauntlet
    r = await gauntlet.churn(seconds=1.2, short=True, threshold=0.15)
    e = r["extra"]
    _check_verdicts(e["verdicts"])
    assert e["errors"] == 0
    assert e["connects"] >= 2
    assert e["all_met"], e["verdicts"]


async def test_gauntlet_hot_key_ledger_names_burner():
    """ISSUE 17 acceptance: Zipf skew against a 2-silo cluster with the
    cost ledger armed — the breach drill-down NAMES the hot key and its
    tenant through get_cluster_ledger's deterministic sketch merge,
    while the QoS lane stays clean (probe SLI, zero false suspicions)."""
    from benchmarks import gauntlet
    r = await gauntlet.hot_key(seconds=2.6, short=True, threshold=0.02)
    e = r["extra"]
    _check_verdicts(e["verdicts"])
    assert e["app_slo_breached"], e["verdicts"]
    # the ledger named WHO: the Zipf rank-0 key, tenant-annotated
    assert e["ledger_names_hot_key"], e["ledger_worst_burner"]
    assert e["ledger_names_tenant"], e["ledger_worst_tenant"]
    assert e["ledger_worst_burner"]["seconds"] > 0
    # and the QoS lane did not pay for the skew
    assert e["false_suspicions"] == 0
    assert e["membership_stable"]
    assert e["qos_invariant_held"], (e["probe_rtt_fast_fraction"],
                                     e["false_suspicions"])
