"""Live metrics pipeline (ISSUE 6): registry under concurrent turns,
histogram quantiles/exposition buckets, sampler windowing, the Prometheus
pull endpoint scrape round-trip, OTLP metrics batching/retry/drop against
a local fake collector, ingest stage attribution over the socket path,
and the cluster-wide merge via ManagementGrain."""

import asyncio

from orleans_tpu.observability.export import (
    OtlpMetricsSink,
    snapshots_to_otlp_metrics,
)
from orleans_tpu.observability.metrics import (
    MetricsSampler,
    WindowedGauge,
    prometheus_exposition,
)
from orleans_tpu.observability.stats import (
    COUNT_BOUNDS,
    INGEST_STATS,
    SIZE_BOUNDS,
    Histogram,
    StatsRegistry,
)
from orleans_tpu.runtime import Grain
from orleans_tpu.testing import TestClusterBuilder


class EchoGrain(Grain):
    async def ping(self, x: int) -> int:
        return x


# ----------------------------------------------------------------------
# Registry + histogram surface
# ----------------------------------------------------------------------
async def test_registry_snapshot_under_concurrent_increments():
    """Counters written from many concurrent tasks stay exact, and a
    snapshot taken mid-flight is a consistent point read (never a torn
    dict)."""
    reg = StatsRegistry()
    N, TASKS = 200, 8

    async def writer(wid: int) -> None:
        for i in range(N):
            reg.increment("t.calls")
            reg.observe("t.lat", 0.001 * (i % 7))
            if i % 32 == 0:
                await asyncio.sleep(0)

    async def snapshotter() -> list[dict]:
        out = []
        for _ in range(20):
            out.append(reg.snapshot())
            await asyncio.sleep(0)
        return out

    results = await asyncio.gather(snapshotter(),
                                   *(writer(w) for w in range(TASKS)))
    assert reg.get("t.calls") == N * TASKS
    assert reg.histogram("t.lat").total == N * TASKS
    for snap in results[0]:
        # monotone, self-consistent mid-flight reads
        assert 0 <= snap["counters"].get("t.calls", 0) <= N * TASKS
        h = snap["histograms"].get("t.lat")
        if h is not None:
            assert sum(h["buckets"]) == h["count"]


def test_histogram_quantile_and_exposition_buckets():
    h = Histogram()
    for v in (0.0002, 0.0002, 0.003, 0.003, 0.003, 0.2):
        h.observe(v)
    assert h.quantile(0.5) == h.percentile(0.5)
    assert h.quantile(0.99) >= h.quantile(0.5)
    labels = h.bucket_labels()
    assert labels[-1] == "+Inf" and "0.0025" in labels
    cum = h.cumulative_counts()
    assert cum == sorted(cum) and cum[-1] == h.total
    # summary carries p50/p95/p99 and per-bucket counts
    s = h.summary()
    assert {"p50", "p95", "p99", "buckets"} <= set(s)


def test_histogram_custom_bounds_round_trip():
    """Size/count-bounded histograms survive snapshot → from_snapshot →
    merge (the cross-silo aggregation path) with their own buckets."""
    a, b = Histogram(SIZE_BOUNDS), Histogram(SIZE_BOUNDS)
    a.observe(100)
    b.observe(70_000)
    ra = Histogram.from_snapshot(a.summary())
    assert ra.bounds == list(SIZE_BOUNDS)
    ra.merge(Histogram.from_snapshot(b.summary()))
    assert ra.total == 2 and sum(ra.counts) == 2
    # exposition uses the carried bounds, not the latency defaults
    assert "65536" in ra.bucket_labels()


def test_registry_histogram_with_bounds_applied_once():
    reg = StatsRegistry()
    h1 = reg.histogram_with("sz", SIZE_BOUNDS)
    h2 = reg.histogram_with("sz", COUNT_BOUNDS)  # second bounds ignored
    assert h1 is h2 and h1.bounds == list(SIZE_BOUNDS)


def test_histogram_mixed_bounds_merge_widens_deterministically():
    """Cluster-merge guard: one silo created a series with SIZE_BOUNDS,
    another with the latency defaults (the first-creation-wins race
    across silos). Merging must widen deterministically — each source
    bucket folds into the target bucket containing its upper bound —
    never mis-bucket positionally or lose counts."""
    target = Histogram()           # latency defaults
    other = Histogram(SIZE_BOUNDS)
    other.observe(100.0)           # -> size bucket le=256
    other.observe(70_000.0)        # -> size bucket le=262144
    before = target.total
    target.merge(other)
    assert target.total == before + 2
    assert sum(target.counts) == 2
    # every count landed in the terminal bucket of the default bounds
    # (both SIZE upper bounds exceed the 30s latency cap -> +Inf), i.e.
    # conservative coarsening, not silent positional mis-bucketing
    assert target.counts[-1] == 2
    # mixed-bounds merge twice is stable (pure widening, no drift)
    t2 = Histogram()
    t2.merge(other).merge(other)
    assert t2.total == 4 and sum(t2.counts) == 4
    # a corrupt snapshot (bucket list disagreeing with its bounds) raises
    # instead of silently mis-stating
    bad = other.summary()
    bad["buckets"] = bad["buckets"][:-2]
    try:
        Histogram.from_snapshot(bad)
    except ValueError:
        pass
    else:  # pragma: no cover
        raise AssertionError("corrupt snapshot accepted")


def test_histogram_exemplars_ride_snapshot_merge_and_exposition():
    """Metrics exemplars: a sampled trace id attaches to the bucket its
    observation landed in, survives snapshot round-trips and cluster
    merge, and renders in OpenMetrics exemplar syntax on the endpoint."""
    h = Histogram()
    h.observe(0.003)
    h.exemplar(0.003, 0xABC)       # slow-ish bucket, trace attached
    s = h.summary()
    assert "exemplars" in s
    r = Histogram.from_snapshot(s)
    assert r.exemplars and list(r.exemplars.values())[0][1] == 0xABC
    # merge keeps the NEWEST exemplar per bucket and re-locates by value
    other = Histogram(SIZE_BOUNDS)
    other.observe(100.0)
    other.exemplar(100.0, 0xDEF)
    r.merge(other)
    assert any(t == 0xDEF for _, t, _ in r.exemplars.values())
    # OpenMetrics rendering carries the exemplar suffix on the bucket
    snap = {"counters": {"c": 1}, "gauges": {}, "histograms":
            {"qw": r.summary()}}
    text = prometheus_exposition(snap, openmetrics=True)
    # 32-hex trace id, the same width the OTLP span export uses, so
    # exemplar -> trace joins match on exact id string
    assert 'trace_id="%032x"' % 0xABC in text
    line = [ln for ln in text.splitlines() if "0abc" in ln][0]
    assert " # {" in line and line.startswith("orleans_qw_bucket")
    assert "orleans_c_total 1" in text and text.rstrip().endswith("# EOF")
    # the classic 0.0.4 rendering stays exemplar-free (strict parsers
    # reject tokens after the sample value outside OpenMetrics)
    plain = prometheus_exposition(snap)
    assert "trace_id" not in plain and "# EOF" not in plain
    assert "orleans_c 1" in plain


# ----------------------------------------------------------------------
# Sampler windowing
# ----------------------------------------------------------------------
def test_windowed_gauge_trims_and_summarizes():
    w = WindowedGauge(window=10.0)
    for i in range(5):
        w.add(float(i), ts=100.0 + i)
    assert w.summary() == {"n": 5, "last": 4.0, "min": 0.0, "max": 4.0,
                           "mean": 2.0}
    w.add(9.0, ts=113.0)  # evicts everything older than 103.0
    s = w.summary()
    assert s["n"] == 3 and s["min"] == 3.0 and s["max"] == 9.0
    assert w.last() == 9.0


async def test_sampler_windows_fill_and_gauges_register():
    cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
               .with_metrics(sample_period=0.03).build())
    async with cluster:
        for i in range(30):
            assert await cluster.grain(EchoGrain, i % 4).ping(i) == i
        await asyncio.sleep(0.15)
        silo = cluster.silos[0]
        sampler = silo.metrics
        assert isinstance(sampler, MetricsSampler) and sampler.ticks >= 2
        windows = sampler.window_snapshot()
        assert windows["queue.inbound.application"]["n"] >= 2
        assert windows["rpc.pending_callbacks"]["n"] >= 2
        assert "sampler.loop_lag" in windows
        # sources double as live registry gauges
        snap = silo.stats.snapshot()
        assert "queue.inbound.application" in snap["gauges"]
        assert "pool.message_free" in snap["gauges"]
        # stage instrumentation observed queue waits for the turns above
        qw = snap["histograms"].get(INGEST_STATS["queue_wait"])
        assert qw is not None and qw["count"] > 0


async def test_sampler_isolates_raising_source():
    cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
               .with_metrics(sample_period=0.03).build())
    async with cluster:
        sampler = cluster.silos[0].metrics

        def boom() -> float:
            raise RuntimeError("injected gauge failure")

        sampler.add_source("test.bad", boom)
        sampler.add_source("test.good", lambda: 7.0)
        sampler.sample_once()
        assert sampler.window_snapshot()["test.good"]["last"] == 7.0
        assert sampler.window_snapshot()["test.bad"]["n"] == 0


# ----------------------------------------------------------------------
# Prometheus endpoint scrape round-trip
# ----------------------------------------------------------------------
def _parse_exposition(text: str) -> dict[str, float]:
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        out[name] = float(value)
    return out


async def test_prometheus_endpoint_scrape_round_trip():
    cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
               .with_metrics(sample_period=0.05, port=0).build())
    async with cluster:
        for i in range(20):
            await cluster.grain(EchoGrain, 0).ping(i)
        silo = cluster.silos[0]
        port = silo.metrics_server.port
        assert port and port > 0

        async def scrape(path: str = "/metrics") -> tuple[str, str]:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
            await writer.drain()
            raw = await reader.read(-1)
            writer.close()
            head, _, body = raw.decode().partition("\r\n\r\n")
            return head, body

        head, body = await scrape()
        assert head.startswith("HTTP/1.1 200")
        assert "text/plain; version=0.0.4" in head
        series = _parse_exposition(body)
        sent = silo.stats.get("messaging.sent")
        label = f'{{silo="{silo.config.name}"}}'
        # counter round-trips exactly (scrape happened after the pings)
        assert series[f"orleans_messaging_sent{label}"] >= 1
        assert series[f"orleans_messaging_sent{label}"] <= sent + 5
        # histogram: cumulative le-buckets, _sum, _count all present
        qw = "orleans_ingest_queue_wait_seconds"
        count_key = f"{qw}_count{label}"
        assert count_key in series and series[count_key] > 0
        inf_key = f'{qw}_bucket{{silo="{silo.config.name}",le="+Inf"}}'
        assert series[inf_key] == series[count_key]
        # live gauges from the sampler sources
        assert f"orleans_rpc_pending_callbacks{label}" in series
        # window summaries exported as _window_* gauges
        assert any(k.startswith(f"{qw}") for k in series)
        head404, _ = await scrape("/nope")
        assert head404.startswith("HTTP/1.1 404")


# ----------------------------------------------------------------------
# OTLP metrics export (fake collector)
# ----------------------------------------------------------------------
from fake_otlp import FakeCollector  # noqa: E402


def _metrics_collector(fail_first: int = 0) -> FakeCollector:
    return FakeCollector(fail_first=fail_first, path="/v1/metrics")


def _snap(silo_name="s0") -> dict:
    reg = StatsRegistry()
    reg.increment("m.calls", 5)
    reg.set_gauge("m.depth", 3.0)
    reg.observe("m.lat", 0.002)
    reg.histogram_with("m.bytes", SIZE_BOUNDS).observe(300)
    snap = reg.snapshot()
    snap["silo"] = silo_name
    return snap


def test_snapshots_to_otlp_metrics_shape():
    req = snapshots_to_otlp_metrics([_snap()], service_name="svc")
    rm = req["resourceMetrics"][0]
    assert rm["resource"]["attributes"][0]["value"]["stringValue"] == "svc"
    metrics = {m["name"]: m for m in rm["scopeMetrics"][0]["metrics"]}
    assert metrics["m.calls"]["sum"]["isMonotonic"] is True
    assert metrics["m.calls"]["sum"]["dataPoints"][0]["asInt"] == "5"
    assert metrics["m.depth"]["gauge"]["dataPoints"][0]["asDouble"] == 3.0
    lat = metrics["m.lat"]["histogram"]["dataPoints"][0]
    assert lat["count"] == "1" and len(lat["bucketCounts"]) == \
        len(lat["explicitBounds"]) + 1
    # custom-bounds histogram carries ITS bounds, not the latency ones
    by = metrics["m.bytes"]["histogram"]["dataPoints"][0]
    assert 65536.0 in by["explicitBounds"]
    # the silo attribute rides per data point
    assert lat["attributes"][0]["value"]["stringValue"] == "s0"


async def test_otlp_metrics_sink_batches_and_retries():
    col = _metrics_collector(fail_first=1)
    try:
        sink = OtlpMetricsSink(col.endpoint, retry_backoff=0.01)
        sink.offer((_snap("a"),))
        sink.offer((_snap("b"),))
        await sink.flush()
        assert sink.exported == 2 and sink.dropped == 0
        assert sink.retries >= 1  # first post failed 503, retried
        assert {"m.calls", "m.lat", "m.bytes"} <= col.metric_names()
        await sink.aclose()
    finally:
        col.close()


async def test_otlp_metrics_sink_drops_when_unreachable():
    sink = OtlpMetricsSink("http://127.0.0.1:1/v1/metrics",
                           max_retries=0, timeout=0.2)
    sink.offer((_snap(),))
    await sink.flush()
    assert sink.exported == 0 and sink.dropped == 1
    await sink.aclose(flush=False)


async def test_silo_pushes_snapshots_to_collector():
    """End to end: a metrics-enabled silo with an OTLP endpoint pushes
    registry snapshots on the sampler cadence; stop flushes a final one."""
    col = _metrics_collector()
    try:
        cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
                   .with_metrics(sample_period=0.03,
                                 otlp_endpoint=col.endpoint,
                                 otlp_period=0.05).build())
        async with cluster:
            for i in range(10):
                await cluster.grain(EchoGrain, 0).ping(i)
            await asyncio.sleep(0.25)
        names = col.metric_names()
        assert "messaging.sent" in names
        assert INGEST_STATS["queue_wait"] in names
    finally:
        col.close()


# ----------------------------------------------------------------------
# Ingest stage attribution over the real socket path
# ----------------------------------------------------------------------
async def test_socket_ingest_stages_observed():
    """Gateway traffic over real TCP populates the decode / enqueue /
    queue_wait stage histograms and the frame-batch size series, and the
    per-stage counts line up with the frames counter."""
    from orleans_tpu.runtime import SiloBuilder
    from orleans_tpu.runtime.socket_fabric import GatewayClient, SocketFabric

    fabric = SocketFabric()
    silo = (SiloBuilder().with_name("ingest-test").with_fabric(fabric)
            .add_grains(EchoGrain)
            .with_config(metrics_enabled=True).build())
    await silo.start()
    client = None
    try:
        client = await GatewayClient(
            [silo.silo_address.endpoint]).connect()
        g = client.get_grain(EchoGrain, 1)
        for i in range(40):
            assert await g.ping(i) == i
        snap = silo.stats.snapshot()
        hists = snap["histograms"]
        decode = hists[INGEST_STATS["decode"]]
        enqueue = hists[INGEST_STATS["enqueue"]]
        qwait = hists[INGEST_STATS["queue_wait"]]
        assert decode["count"] >= 40
        assert enqueue["count"] == decode["count"]
        assert qwait["count"] >= 40
        assert snap["counters"][INGEST_STATS["frames"]] == decode["count"]
        # size + batch histograms carry their custom bounds
        dbytes = hists[INGEST_STATS["decode_bytes"]]
        assert dbytes["count"] == decode["count"]
        assert dbytes["bounds"][0] == 64.0
        batch = hists[INGEST_STATS["frame_batch"]]
        assert batch["count"] >= 1 and batch["sum"] == decode["count"]
        # stages are real time: every sum is positive and finite
        for h in (decode, enqueue, qwait):
            assert 0 < h["sum"] < 60
    finally:
        if client is not None:
            await client.close_async()
        await silo.stop()


async def test_vector_ingest_stages_observed():
    """Device-tier calls through a metrics-enabled silo populate the
    staging / transfer / tick stage histograms and the ingest.messages
    counter (the device half of the attribution)."""
    import jax.numpy as jnp

    from orleans_tpu.dispatch import (VectorGrain, actor_method,
                                      add_vector_grains)
    from orleans_tpu.parallel import make_mesh
    from orleans_tpu.runtime import ClusterClient, SiloBuilder

    class CounterVec(VectorGrain):
        STATE = {"count": (jnp.int32, ())}

        @staticmethod
        def initial_state(key_hash):
            return {"count": jnp.int32(0)}

        @actor_method(args={"x": (jnp.int32, ())})
        def add(state, args):
            return {"count": state["count"] + args["x"]}, state["count"]

    b = (SiloBuilder().with_name("vec-metrics")
         .with_config(metrics_enabled=True))
    add_vector_grains(b, CounterVec, mesh=make_mesh(1))
    silo = b.build()
    await silo.start()
    client = None
    try:
        client = await ClusterClient(silo.fabric).connect()
        await asyncio.gather(*(client.get_grain(CounterVec, k).add(x=1)
                               for k in range(16)))
        snap = silo.stats.snapshot()
        hists = snap["histograms"]
        for stage in ("staging", "transfer", "tick"):
            h = hists.get(INGEST_STATS[stage])
            assert h is not None and h["count"] >= 1, stage
        assert snap["counters"][INGEST_STATS["messages"]] >= 16
        assert hists[INGEST_STATS["queue_wait"]]["count"] >= 16
    finally:
        if client is not None:
            await client.close_async()
        await silo.stop()


# ----------------------------------------------------------------------
# Cluster-wide merge via ManagementGrain
# ----------------------------------------------------------------------
async def test_management_grain_merges_cluster_metrics():
    from orleans_tpu.management import ManagementGrain

    cluster = (TestClusterBuilder(2).add_grains(EchoGrain)
               .with_metrics(sample_period=0.05).build())
    async with cluster:
        for i in range(40):
            await cluster.grain(EchoGrain, i).ping(i)
        await asyncio.sleep(0.12)
        mg = cluster.client.get_grain(ManagementGrain, 0)
        merged = await mg.get_cluster_metrics()
        per_silo = merged["per_silo"]
        assert len(per_silo) == 2
        # counters sum across silos exactly
        sent = sum(s["counters"].get("messaging.sent", 0)
                   for s in per_silo.values())
        assert merged["counters"]["messaging.sent"] == sent > 0
        # histograms fold losslessly (bucket-wise) across silos
        qw_name = INGEST_STATS["queue_wait"]
        total = sum(s["histograms"].get(qw_name, {}).get("count", 0)
                    for s in per_silo.values())
        assert merged["histograms"][qw_name]["count"] == total > 0
        # per-silo payloads carry sampler windows for drill-down
        for s in per_silo.values():
            assert "windows" in s and "rpc.pending_callbacks" in s["windows"]
        # gauges aggregate as sums (queue depth: cluster total)
        assert "rpc.pending_callbacks" in merged["gauges"]


async def test_metrics_disabled_costs_nothing_structural():
    """With metrics off (the default), no sampler/server is installed,
    ingest_stats is None on every hot-path holder, and no ingest stage
    histograms appear."""
    cluster = TestClusterBuilder(1).add_grains(EchoGrain).build()
    async with cluster:
        silo = cluster.silos[0]
        assert silo.metrics is None and silo.metrics_server is None
        assert silo.ingest_stats is None
        assert silo.dispatcher._istats is None
        await cluster.grain(EchoGrain, 1).ping(1)
        snap = silo.stats.snapshot()
        assert INGEST_STATS["queue_wait"] not in snap["histograms"]
