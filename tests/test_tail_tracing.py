"""Tail-based trace retention + streaming OTLP export (observability
tracing/export, ISSUE 5): slow/errored traces survive the tail decision
while fast-clean ones drop; straggler legs inside the quiescence window
join; cross-silo legs pull over the real control path when a silo retains
a trace; OtlpSink batching/retry/drop against a local fake collector;
rejection/resend span events; the response-leg network span; and the
sampled-trace hot lane rolling the head die inside the lane."""

import asyncio
import time

import pytest

from orleans_tpu.core.message import RejectionType, make_rejection
from orleans_tpu.management import ManagementGrain
from orleans_tpu.observability.export import OtlpSink, spans_to_otlp
from orleans_tpu.observability.tracing import (
    LatencyErrorPolicy,
    SpanCollector,
)
from orleans_tpu.runtime import Grain
from orleans_tpu.runtime.runtime_client import RuntimeClient
from orleans_tpu.testing import TestClusterBuilder


class EchoGrain(Grain):
    async def ping(self, x: int) -> int:
        return x


class SlowGrain(Grain):
    async def nap(self) -> str:
        await asyncio.sleep(0.12)
        return "slept"


class FailGrain(Grain):
    async def boom(self) -> None:
        raise ValueError("injected failure")


class SlowEchoGrain(Grain):
    async def ping(self, x: int) -> int:
        await asyncio.sleep(0.1)
        return x


class ProxyGrain(Grain):
    async def relay(self, key: int, x: int) -> int:
        return await self.get_grain(SlowEchoGrain, key).ping(x)


# ----------------------------------------------------------------------
# Tentpole acceptance: slow + errored survive the tail, fast-clean drops
# ----------------------------------------------------------------------
async def test_tail_keeps_slow_and_errored_drops_fast_clean():
    """ISSUE 5 acceptance: tail mode, head rate 1.0-record/0-keep — the
    injected slow and failing requests export with ALL legs while >=95%
    of fast-clean traces drop, and kept/dropped counts are visible via
    the ManagementGrain."""
    n_fast = 60
    cluster = (TestClusterBuilder(1)
               .add_grains(EchoGrain, SlowGrain, FailGrain)
               .with_tracing(tail=True, tail_window=0.15,
                             slow_threshold=0.05, leg_ttl=0.5)
               .build())
    async with cluster:
        assert await cluster.grain(SlowGrain, 1).nap() == "slept"
        with pytest.raises(ValueError):
            await cluster.grain(FailGrain, 2).boom()
        for i in range(n_fast):
            assert await cluster.grain(EchoGrain, i % 8).ping(i) == i

        ct = cluster.client.tracer
        # nothing committed yet: the decision waits for the tail
        assert ct.retention_stats()["tail"] is True
        await cluster.drain_traces()

        spans = ct.snapshot()
        names = {s["name"] for s in spans}
        assert "SlowGrain.nap" in names and "FailGrain.boom" in names
        # all legs retained, including the silo-side server turns (pulled
        # off the silo collector at retention time) and network legs
        kept_tids = {s["trace_id"] for s in spans}
        assert len(kept_tids) == 2
        for tid in kept_tids:
            kinds = {s["kind"] for s in spans if s["trace_id"] == tid}
            assert {"client", "server", "network"} <= kinds
            silos = {s["silo"] for s in spans if s["trace_id"] == tid}
            assert "silo0" in silos and "client" in silos
        # the errored trace carries the error attr; the slow one the
        # retention reason
        reasons = {s["attrs"].get("retained") for s in spans
                   if s["parent_id"] is None}
        assert reasons == {"slow", "error"}

        st = ct.retention_stats()
        assert st["kept"] == 2
        assert st["dropped"] >= n_fast * 0.95

        # cluster-wide counters through the management surface: the two
        # retained traces were PULLED off the silo (kept there too), the
        # fast-clean legs expired un-pulled (dropped there)
        mgmt = cluster.grain(ManagementGrain, 0)
        stats = await mgmt.get_retention_stats()
        totals = stats["totals"]
        assert totals["kept"] >= 2 and totals["pulled"] >= 2
        assert totals["dropped"] >= n_fast * 0.95
        assert len(stats["per_silo"]) == 1


async def test_tail_forced_retention_survives_policy_drop():
    cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
               .with_tracing(tail=True, tail_window=0.1,
                             slow_threshold=10.0, leg_ttl=0.4)
               .build())
    async with cluster:
        assert await cluster.grain(EchoGrain, 1).ping(1) == 1
        ct = cluster.client.tracer
        tid = next(iter(ct.pending))
        ct.force_retain(tid)
        assert await cluster.grain(EchoGrain, 1).ping(2) == 2
        await cluster.drain_traces()
        st = ct.retention_stats()
        assert st["kept"] == 1 and st["dropped"] >= 1
        roots = [s for s in ct.snapshot() if s["parent_id"] is None]
        assert len(roots) == 1 and roots[0]["attrs"]["retained"] == "forced"


# ----------------------------------------------------------------------
# Straggler legs + quiescence window (collector-level, loop-less)
# ----------------------------------------------------------------------
def test_straggler_leg_within_quiescence_window_included():
    c = SpanCollector("s", tail=True, tail_window=0.05,
                      policy=LatencyErrorPolicy(slow_threshold=0.01))
    root = c.open("op", "client", trace_id=7, parent_id=None)
    c.close(root, duration=0.5)          # slow: will be retained
    # straggler (e.g. the response-leg network span) lands AFTER the root
    # closed but inside the window — it must ride along
    c.record(7, root.span_id, "network", "network", time.time(), 0.001,
             leg="response")
    c.flush_tail()                       # window not elapsed: no decision
    assert c.retention_stats()["kept"] == 0 and len(c.pending) == 1
    time.sleep(0.06)
    c.flush_tail()                       # quiesced now: decide
    st = c.retention_stats()
    assert st["kept"] == 1 and st["buffered"] == 0
    got = c.snapshot(trace_id=7)
    assert {s["kind"] for s in got} == {"client", "network"}

    # a leg arriving after the decision starts a leg-only entry that can
    # only expire (its trace was already decided elsewhere)
    c.record(7, root.span_id, "network", "network", time.time(), 0.001)
    c.flush_tail(force=True)
    assert c.retention_stats()["dropped"] == 1


def test_device_tick_trace_bypasses_tail_stage():
    """The synthetic device-tick trace (endless parent-less spans on one
    shared trace_id) must land straight in the bounded ring even in tail
    mode — buffering it would re-arm the quiescence window forever and
    grow one pending entry without bound."""
    c = SpanCollector("s", tail=True, tail_window=10.0)
    for i in range(50):
        c.record(c.device_trace_id, None, f"tick{i}", "device_tick",
                 time.time(), 0.001, batch=1)
    assert len(c.pending) == 0
    assert len(c.spans) == 50
    assert c.retention_stats()["kept"] == 0  # telemetry, not retention


def test_pull_leaves_locally_rooted_pending_trace_for_its_own_decision():
    """An operator peeking at a live trace id (ctl_trace_spans in tail
    mode) must not steal a HERE-rooted trace from its own tail decision
    and sink export — only leg-only entries promote on pull."""
    c = SpanCollector("s", tail=True, tail_window=0.02,
                      policy=LatencyErrorPolicy(slow_threshold=0.01))
    root = c.open("op", "client", trace_id=9, parent_id=None)
    c.close(root, duration=0.5)
    got = c.pull(9)
    assert len(got) == 1                       # read-only view
    assert 9 in c.pending                      # still owns its decision
    assert c.retention_stats()["pulled"] == 0
    time.sleep(0.03)
    c.flush_tail()
    assert c.retention_stats()["kept"] == 1    # normal retention ran


def test_tail_pending_buffer_is_bounded():
    c = SpanCollector("s", tail=True, max_pending=8)
    for i in range(20):
        c.close(c.open(f"op{i}", "server", trace_id=1000 + i,
                       parent_id=1))    # leg-only: never decided
    assert len(c.pending) == 8
    assert c.retention_stats()["dropped"] == 12  # evicted oldest


def test_latency_policy_percentile_mode():
    pol = LatencyErrorPolicy(slow_threshold=0.0, slow_percentile=0.9)
    c = SpanCollector("s", tail=True, tail_window=0.0, policy=pol)

    def one(dur):
        root = c.open("op", "client", trace_id=c.new_trace_id(),
                      parent_id=None)
        c.close(root, duration=dur)
        c.flush_tail(force=True)

    for _ in range(30):
        one(0.001)                      # build history: all fast
    kept_before = c.retention_stats()["kept"]
    one(1.0)                            # way past p90 of history
    assert c.retention_stats()["kept"] == kept_before + 1


# ----------------------------------------------------------------------
# Cross-silo leg pull over the REAL control path (silo-rooted trace)
# ----------------------------------------------------------------------
async def test_cross_silo_leg_pull_via_control_path():
    """Client untraced -> the relay silo roots the trace for its outgoing
    call; the callee runs on the OTHER silo; retention at the rooting silo
    pulls the remote server leg via ctl_trace_spans (SYSTEM RPC), which
    also promotes/counts it kept on the remote side."""
    cluster = (TestClusterBuilder(2).add_grains(ProxyGrain, SlowEchoGrain)
               .with_tracing(tail=True, tail_window=0.15,
                             slow_threshold=0.05, leg_ttl=1.0,
                             client=False)
               .build())
    async with cluster:
        assert cluster.client.tracer is None  # traces must root silo-side
        pair = None
        for key in range(16):
            assert await cluster.grain(ProxyGrain, key).relay(key, 5) == 5
            proxy_gid = cluster.grain(ProxyGrain, key).grain_id
            echo_gid = cluster.grain(SlowEchoGrain, key).grain_id
            hosts = {}
            for s in cluster.silos:
                if s.catalog.by_grain.get(proxy_gid):
                    hosts["proxy"] = s
                if s.catalog.by_grain.get(echo_gid):
                    hosts["echo"] = s
            if len(hosts) == 2 and hosts["proxy"] is not hosts["echo"]:
                pair = (hosts["proxy"], hosts["echo"])
                break
        assert pair is not None, "no cross-silo placement in 16 keys"
        rooter, remote = pair

        await cluster.drain_traces()
        # the rooting silo retained the slow trace WITH the remote leg
        retained = rooter.tracer.snapshot()
        assert any(s["parent_id"] is None
                   and s["attrs"].get("retained") == "slow"
                   and s["name"] == "SlowEchoGrain.ping"
                   for s in retained), retained
        remote_legs = [s for s in retained
                       if s["silo"] == remote.config.name
                       and s["kind"] == "server"]
        assert remote_legs, "remote server leg was not pulled"
        # the pull handed the legs off (counted kept, not expired)...
        assert remote.tracer.retention_stats()["pulled"] >= 1
        # ...without double-storing them: exactly one collector (the
        # puller) holds a pulled trace, so cluster-wide merges
        # (get_trace_spans / export_trace) never count a leg twice
        pulled_tids = {s["trace_id"] for s in remote_legs}
        assert not [s for s in remote.tracer.snapshot()
                    if s["trace_id"] in pulled_tids]


async def test_pull_dedups_span_ids_across_fanout(monkeypatch):
    """Cross-process span-level dedup (ISSUE 18 satellite): worker-process
    silos make duplicate pulls real — a forwarded leg (or a span a peer
    itself pulled and retained) can come back from MORE THAN ONE silo in
    the ctl_trace_spans fan-out, and export must not double-count it.
    The retained-trace pull keeps the first copy of each span_id."""
    from orleans_tpu.core.ids import SiloAddress
    from orleans_tpu.runtime import SiloBuilder

    silo = (SiloBuilder().with_name("dedup")
            .with_config(trace_enabled=True, trace_tail_enabled=True)
            .build())
    a1 = SiloAddress("127.0.0.1", 11, 1)
    a2 = SiloAddress("127.0.0.1", 22, 1)
    silo.locator.alive_list = [silo.silo_address, a1, a2]

    def leg(sid):
        return {"trace_id": 7, "span_id": sid, "parent_id": None,
                "name": f"op{sid}", "kind": "server", "silo": "w",
                "start": 0.0, "duration": 0.1, "attrs": {}}

    async def fake_send_request(**kw):
        # peer 1 and peer 2 both hold span 101 (one forwarded its leg
        # through the other); 102 lacks a span_id and must pass through
        if kw["target_silo"] == a1:
            return [leg(100), leg(101)]
        return [leg(101), leg(103), {"trace_id": 7, "attrs": {}}]

    monkeypatch.setattr(silo.runtime_client, "send_request",
                        fake_send_request)
    out = await silo._pull_trace_legs(7)
    assert [d.get("span_id") for d in out] == [100, 101, 103, None]


# ----------------------------------------------------------------------
# OTLP sink: batching / payload shape / retry / drop
# ----------------------------------------------------------------------
from fake_otlp import FakeCollector as _FakeCollector  # noqa: E402


def _mk_span_dicts(n, trace_id=0xabc, error_on=None, events_on=None):
    out = []
    for i in range(n):
        d = {"trace_id": trace_id, "span_id": 100 + i,
             "parent_id": 99 if i else None, "name": f"op{i}",
             "kind": "server" if i else "client", "silo": "silo0",
             "start": 1000.0 + i, "duration": 0.25, "attrs": {"n": i}}
        if error_on is not None and i == error_on:
            d["attrs"]["error"] = "ValueError"
        if events_on is not None and i == events_on:
            d["events"] = [["resend", 1000.5, {"rejection": "TRANSIENT"}]]
        out.append(d)
    return out


def test_otlp_payload_shape():
    payload = spans_to_otlp(_mk_span_dicts(2, error_on=1, events_on=1),
                            service_name="svc")
    rs = payload["resourceSpans"][0]
    res_attrs = {a["key"]: a["value"] for a in rs["resource"]["attributes"]}
    assert res_attrs["service.name"] == {"stringValue": "svc"}
    spans = rs["scopeSpans"][0]["spans"]
    assert len(spans) == 2
    root, child = spans
    assert len(root["traceId"]) == 32 and len(root["spanId"]) == 16
    assert "parentSpanId" not in root and len(child["parentSpanId"]) == 16
    assert root["kind"] == 3 and child["kind"] == 2  # CLIENT / SERVER
    assert int(child["endTimeUnixNano"]) - int(child["startTimeUnixNano"]) \
        == int(0.25 * 1e9)
    assert child["status"] == {"code": 2, "message": "ValueError"}
    assert child["events"][0]["name"] == "resend"
    span_attrs = {a["key"] for a in child["attributes"]}
    assert {"n", "orleans.silo", "orleans.kind"} <= span_attrs


async def test_otlp_sink_batches_to_local_collector():
    col = _FakeCollector()
    try:
        sink = OtlpSink(col.endpoint, batch_size=4, flush_interval=0.05)
        sink.offer(_mk_span_dicts(6))
        # offer kicked the background flusher (full batch) — settle on the
        # counters instead of racing it with an explicit flush
        for _ in range(200):
            if sink.stats()["exported"] >= 6:
                break
            await asyncio.sleep(0.01)
        assert col.span_count() == 6
        sizes = sorted(
            len(ss["spans"])
            for b in col.bodies for rs in b["resourceSpans"]
            for ss in rs["scopeSpans"])
        assert sizes == [2, 4]  # bounded batches, nothing lost
        st = sink.stats()
        assert st["exported"] == 6 and st["export_batches"] == 2
        assert st["export_dropped"] == 0
        await sink.aclose()
    finally:
        col.close()


async def test_otlp_sink_retries_transient_failure():
    col = _FakeCollector(fail_first=1)
    try:
        sink = OtlpSink(col.endpoint, batch_size=8, max_retries=2,
                        retry_backoff=0.01)
        sink.offer(_mk_span_dicts(3))
        await sink.flush()
        st = sink.stats()
        assert st["exported"] == 3 and st["export_dropped"] == 0
        assert st["export_retries"] >= 1
        await sink.aclose()
    finally:
        col.close()


async def test_otlp_sink_drops_and_counts_when_unreachable():
    # closed port: connection refused immediately, no real network
    sink = OtlpSink("http://127.0.0.1:9/v1/traces", batch_size=4,
                    max_retries=1, retry_backoff=0.01, timeout=0.2)
    sink.offer(_mk_span_dicts(5))
    await sink.flush()   # must not raise
    st = sink.stats()
    assert st["exported"] == 0 and st["export_dropped"] == 5
    await sink.aclose()


async def test_otlp_sink_queue_overflow_drops_oldest():
    sink = OtlpSink("http://127.0.0.1:9/v1/traces", max_queue=4)
    sink.offer(_mk_span_dicts(6))
    assert sink.stats()["queued"] == 4
    assert sink.stats()["export_dropped"] == 2
    await sink.aclose(flush=False)


async def test_tail_cluster_streams_retained_trace_to_collector():
    """End to end: tail cluster + OTLP endpoint — the retained slow trace
    (with its pulled silo legs) lands at the collector; dropped fast-clean
    traces never ship."""
    col = _FakeCollector()
    try:
        cluster = (TestClusterBuilder(1).add_grains(EchoGrain, SlowGrain)
                   .with_tracing(tail=True, tail_window=0.1,
                                 slow_threshold=0.05, leg_ttl=0.4,
                                 otlp_endpoint=col.endpoint)
                   .build())
        async with cluster:
            assert await cluster.grain(SlowGrain, 1).nap() == "slept"
            for i in range(10):
                assert await cluster.grain(EchoGrain, 1).ping(i) == i
            await cluster.drain_traces()
            shipped = [sp for b in col.bodies
                       for rs in b["resourceSpans"]
                       for ss in rs["scopeSpans"] for sp in ss["spans"]]
            names = {s["name"] for s in shipped}
            assert "SlowGrain.nap" in names
            assert not any("EchoGrain" in n for n in names)
            # the pulled silo leg shipped too (whole trace, one shipper)
            silos = {a["value"]["stringValue"] for s in shipped
                     for a in s["attributes"] if a["key"] == "orleans.silo"}
            assert "silo0" in silos
            st = cluster.client.tracer.retention_stats()
            assert st["exported"] == len(shipped) > 0
    finally:
        col.close()


# ----------------------------------------------------------------------
# Span events: rejections + transient resends (runtime_client side)
# ----------------------------------------------------------------------
class _LoopbackClient(RuntimeClient):
    """Captures transmits so tests can hand-deliver responses."""

    def __init__(self):
        super().__init__(response_timeout=5.0)
        self.sent = []

    @property
    def silo_address(self):
        return None

    def transmit(self, msg):
        self.sent.append(msg)


async def test_resend_and_rejected_events_attach_to_client_span():
    client = _LoopbackClient()
    tracer = client.enable_tracing(1.0)
    res = client.send_request(
        target_grain=None, grain_class=EchoGrain,
        interface_name="EchoGrain", method_name="ping",
        args=(1,), kwargs={})
    req = client.sent[-1]
    cb = client.callbacks[req.id]
    assert cb.span is not None

    # transient rejection: resend scheduled + "rejected"/"resend" events
    client.receive_response(
        make_rejection(req, RejectionType.TRANSIENT, "silo dying"))
    assert [e[0] for e in cb.span.events] == ["rejected", "resend"]
    assert cb.span.events[1][2]["rejection"] == "TRANSIENT"
    assert req.id in client.callbacks  # still outstanding (retrying)

    # exhaust the resend budget -> terminal rejection, span errored
    from orleans_tpu.runtime.runtime_client import MAX_RESEND_COUNT
    cb.message.resend_count = MAX_RESEND_COUNT
    client.receive_response(
        make_rejection(req, RejectionType.TRANSIENT, "still dying"))
    from orleans_tpu.core.errors import RejectionError
    with pytest.raises(RejectionError):
        await res
    spans = tracer.snapshot()
    root = [s for s in spans if s["kind"] == "client"][-1]
    assert root["attrs"]["error"] == "RejectionError"
    names = [e[0] for e in root["events"]]
    assert names.count("rejected") == 2 and "resend" in names


async def test_overload_rejection_records_event_span_server_side():
    class BusyGrain(Grain):
        async def work(self):
            await asyncio.sleep(0.2)
            return 1

    cluster = (TestClusterBuilder(1).add_grains(BusyGrain)
               .with_config(max_enqueued_requests=1)
               .with_tracing().build())
    async with cluster:
        g = cluster.grain(BusyGrain, 1)
        results = await asyncio.gather(*(g.work() for _ in range(5)),
                                       return_exceptions=True)
        assert any(isinstance(r, Exception) for r in results)
        assert any(r == 1 for r in results)
        # the silo annotated the overload rejection under the caller's
        # invoke span; the client's span carries the rejected event
        silo_events = [s for s in cluster.silos[0].tracer.snapshot()
                       if s["kind"] == "event" and s["name"] == "reject"]
        assert silo_events and \
            silo_events[0]["attrs"]["type"] == "OVERLOADED"
        client_roots = [s for s in cluster.client.tracer.snapshot()
                        if s["kind"] == "client" and s.get("events")]
        assert any(e[0] == "rejected" for s in client_roots
                   for e in s["events"])


# ----------------------------------------------------------------------
# Response-leg network span
# ----------------------------------------------------------------------
async def test_response_leg_network_span_recorded():
    cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
               .with_tracing().build())
    async with cluster:
        assert await cluster.grain(EchoGrain, 1).ping(7) == 7
        spans = cluster.trace_spans()
        nets = [s for s in spans if s["kind"] == "network"]
        legs = [s for s in nets if s["attrs"].get("leg") == "response"]
        assert legs, f"no response-leg network span in {nets}"
        # recorded on the RECEIVING side (the client observed arrival),
        # parented under the server turn span that stamped it
        assert legs[-1]["silo"] == "client"
        server_ids = {s["span_id"] for s in spans if s["kind"] == "server"}
        assert legs[-1]["parent_id"] in server_ids


# ----------------------------------------------------------------------
# Sampled-trace hot lane: the lane rolls the die itself
# ----------------------------------------------------------------------
async def test_hotlane_serves_unsampled_majority_at_low_rate():
    cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
               .with_tracing(sample_rate=0.01).build())
    async with cluster:
        g = cluster.grain(EchoGrain, 1)
        assert await g.ping(0) == 0    # activate (always messaging)
        client = cluster.client
        h0, f0 = client.hot_hits, client.hot_fallbacks
        n = 300
        for i in range(n):
            assert await g.ping(i) == i
        hits = client.hot_hits - h0
        falls = client.hot_fallbacks - f0
        assert hits + falls == n
        # binomial(300, 0.99): the lane must keep the unsampled majority
        assert hits >= n * 0.8, (hits, falls)
        # every fallback IS a sampled call: exactly that many root client
        # spans were recorded (the roll is handed over, never re-rolled)
        roots = [s for s in client.tracer.snapshot()
                 if s["kind"] == "client" and s["parent_id"] is None]
        assert len(roots) == falls


async def test_hotlane_rate_zero_and_one_unchanged():
    for rate, expect_hot in ((0.0, True), (1.0, False)):
        cluster = (TestClusterBuilder(1).add_grains(EchoGrain)
                   .with_tracing(sample_rate=rate).build())
        async with cluster:
            g = cluster.grain(EchoGrain, 1)
            assert await g.ping(0) == 0
            h0 = cluster.client.hot_hits
            for i in range(20):
                await g.ping(i)
            engaged = cluster.client.hot_hits - h0 == 20
            assert engaged is expect_hot, (rate, engaged)


# ----------------------------------------------------------------------
# Adaptive tail threshold (trace_tail_auto) — ISSUE 6 satellite
# ----------------------------------------------------------------------
def test_latency_policy_auto_threshold_adapts_down_and_retains_outlier():
    """Auto mode converges slow_threshold onto the root-duration
    percentile cut: a badly hand-set threshold (10s) self-tunes down to
    the workload's actual latency band, after which a real outlier
    retains while the uniform baseline keeps dropping."""
    pol = LatencyErrorPolicy(slow_threshold=10.0, auto=True)
    c = SpanCollector("s", tail=True, tail_window=0.0, policy=pol)

    def one(dur):
        root = c.open("op", "client", trace_id=c.new_trace_id(),
                      parent_id=None)
        c.close(root, duration=dur)
        c.flush_tail(force=True)

    for _ in range(64):
        one(0.01)                       # uniform fast workload
    assert c.retention_stats()["kept"] == 0   # strictly-above: all drop
    assert pol.slow_threshold < 0.1           # converged down from 10.0
    one(0.2)                                  # 20x outlier
    assert c.retention_stats()["kept"] == 1
    root = [s for s in c.snapshot() if s["parent_id"] is None][0]
    assert root["attrs"]["retained"] == "slow_auto"


def test_latency_policy_auto_uses_static_threshold_until_warm():
    """Below _MIN_HISTORY roots the configured static threshold applies
    unchanged (no percentile to tune against yet)."""
    pol = LatencyErrorPolicy(slow_threshold=0.05, auto=True)
    c = SpanCollector("s", tail=True, tail_window=0.0, policy=pol)
    root = c.open("op", "client", trace_id=c.new_trace_id(),
                  parent_id=None)
    c.close(root, duration=0.2)   # > static threshold, history cold
    c.flush_tail(force=True)
    assert c.retention_stats()["kept"] == 1
    assert pol.slow_threshold == 0.05  # untouched before warm-up


async def test_tail_auto_knob_wires_through_silo_config():
    from orleans_tpu.runtime import SiloBuilder

    silo = (SiloBuilder().with_name("auto-tail")
            .with_config(trace_enabled=True, trace_tail_enabled=True,
                         trace_tail_auto=True).build())
    assert silo.tracer.policy.auto is True


# ----------------------------------------------------------------------
# Local-trace pull skip ("went remote" hint) — ISSUE 6 satellite
# ----------------------------------------------------------------------
async def test_retention_pull_skipped_for_local_trace_and_runs_for_remote():
    fetched = []

    async def fetcher(tid):
        fetched.append(tid)
        return []

    pol = LatencyErrorPolicy(slow_threshold=1e-9)  # keep everything
    c = SpanCollector("s", tail=True, tail_window=0.0, policy=pol)
    c.remote_fetcher = fetcher

    # trace 1: never marked remote -> retained WITHOUT fanning the pull
    t1 = c.new_trace_id()
    c.close(c.open("local", "client", t1, None), duration=0.01)
    c.flush_tail(force=True)
    await c.drain_tail()
    assert c.retention_stats()["kept"] == 1
    assert c.retention_stats()["pull_skipped"] == 1
    assert fetched == []

    # trace 2: marked remote BEFORE any span closed (hint path) -> pulled
    t2 = c.new_trace_id()
    c.mark_remote(t2)
    c.close(c.open("remote", "client", t2, None), duration=0.01)
    c.flush_tail(force=True)
    await c.drain_tail()
    assert fetched == [t2]
    assert c.retention_stats()["kept"] == 2
    assert c.retention_stats()["pull_skipped"] == 1

    # trace 3: marked remote AFTER a leg closed (live pending entry)
    t3 = c.new_trace_id()
    c.close(c.open("child", "server", t3, 7), duration=0.001)
    c.mark_remote(t3)
    c.close(c.open("root", "client", t3, None), duration=0.01)
    c.flush_tail(force=True)
    await c.drain_tail()
    assert fetched == [t2, t3]


async def test_silo_local_trace_skips_control_path_fanout():
    """A silo-rooted trace whose call never leaves the silo retains
    without the ctl_trace_spans fan-out (pull_skipped counts it); the
    spans are all local so the export is already whole."""
    cluster = (TestClusterBuilder(1).add_grains(ProxyGrain, SlowEchoGrain)
               .with_tracing(tail=True, tail_window=0.1,
                             slow_threshold=0.05, client=False)
               .build())
    async with cluster:
        silo = cluster.silos[0]
        pulls = []
        real_fetcher = silo.tracer.remote_fetcher
        assert real_fetcher is not None

        async def spying_fetcher(tid):
            pulls.append(tid)
            return await real_fetcher(tid)

        silo.tracer.remote_fetcher = spying_fetcher
        # ProxyGrain.relay roots the trace silo-side; SlowEchoGrain lives
        # on the same (only) silo, so no leg ever crosses the fabric
        assert await cluster.grain(ProxyGrain, 1).relay(1, 5) == 5
        await cluster.drain_traces()
        stats = silo.tracer.retention_stats()
        assert stats["kept"] >= 1
        assert stats["pull_skipped"] >= 1
        assert pulls == []  # the fan-out never ran
        # the retained trace is complete: root + callee server turn
        spans = silo.tracer.snapshot()
        tids = {s["trace_id"] for s in spans if s["parent_id"] is None}
        assert any(s["kind"] == "server" and s["trace_id"] in tids
                   for s in spans)


# ----------------------------------------------------------------------
# OTLP protobuf encoding (ISSUE 20): opt-in binary wire format built
# from the SAME request dicts as the JSON path — a generic wire-walk
# parser (no generated proto classes) proves the framing is valid
# protobuf and carries the same structure the JSON payload does.
# ----------------------------------------------------------------------
def _pb_walk(data: bytes) -> list:
    """Decode one protobuf message into [(field, wire_type, value)]:
    varints as ints, length-delimited as raw bytes, fixed64 as 8 bytes.
    Raises on truncation/invalid tags — the structural validity check."""
    out = []
    i = 0
    while i < len(data):
        tag = 0
        shift = 0
        while True:
            b = data[i]
            i += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
        elif wt == 1:  # fixed64
            v = data[i:i + 8]
            assert len(v) == 8
            i += 8
        elif wt == 2:  # length-delimited
            n = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                n |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            v = data[i:i + n]
            assert len(v) == n, "truncated length-delimited field"
            i += n
        else:
            raise AssertionError(f"unexpected wire type {wt}")
        out.append((field, wt, v))
    return out


def _pb_fields(data: bytes, field: int) -> list:
    return [v for f, _, v in _pb_walk(data) if f == field]


def test_otlp_trace_protobuf_wire_walk():
    """The binary trace encoding is valid protobuf mirroring the JSON
    request: ResourceSpans(resource=1, scope_spans=2) > ScopeSpans >
    Span with ids/name/kind/times/attributes, and the hex trace id
    round-trips into the Span.trace_id bytes."""
    from orleans_tpu.observability.export import otlp_trace_protobuf

    req = spans_to_otlp(_mk_span_dicts(2, error_on=1, events_on=1),
                        service_name="svc")
    data = otlp_trace_protobuf(req)
    (rs,) = _pb_fields(data, 1)           # ExportTraceServiceRequest
    assert _pb_fields(rs, 1)              # resource present
    (ss,) = _pb_fields(rs, 2)             # one ScopeSpans
    spans = _pb_fields(ss, 2)
    assert len(spans) == 2
    json_root = req["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    root, child = spans
    (tid_bytes,) = _pb_fields(root, 1)
    assert tid_bytes == bytes.fromhex(json_root["traceId"])
    assert len(_pb_fields(root, 2)[0]) == 8          # span_id: 8 bytes
    assert not _pb_fields(root, 4)                   # root: no parent
    assert len(_pb_fields(child, 4)[0]) == 8         # child parented
    (name,) = _pb_fields(root, 5)
    assert name == json_root["name"].encode()
    # fixed64 start/end nanos match the JSON stamps
    import struct
    (start,) = _pb_fields(root, 7)
    assert struct.unpack("<Q", start)[0] == \
        int(json_root["startTimeUnixNano"])
    assert _pb_fields(root, 9)                       # attributes
    assert _pb_fields(child, 11)                     # child's event
    assert _pb_fields(child, 15)                     # error → status


def test_otlp_metrics_protobuf_wire_walk():
    """The binary metrics encoding carries sum/gauge/histogram points
    with the same counts and bounds as the JSON request."""
    import struct

    from orleans_tpu.observability.export import (otlp_metrics_protobuf,
                                                  snapshots_to_otlp_metrics)
    from orleans_tpu.observability.stats import Histogram

    h = Histogram()
    for v in (0.001, 0.01, 0.01, 0.2):
        h.observe(v)
    snap = {"ts": 1234.5, "silo": "s0",
            "counters": {"msgs": 7}, "gauges": {"backlog": 2.5},
            "histograms": {"lat": h.summary()}}
    req = snapshots_to_otlp_metrics([snap], service_name="svc")
    data = otlp_metrics_protobuf(req)
    (rm,) = _pb_fields(data, 1)
    (sm,) = _pb_fields(rm, 2)
    metrics = _pb_fields(sm, 2)
    kinds = {}
    for m in metrics:
        (name,) = _pb_fields(m, 1)
        kinds[name.decode()] = {5: "gauge", 7: "sum", 9: "histogram"}[
            next(f for f, _, _ in _pb_walk(m) if f in (5, 7, 9))]
    assert kinds == {"msgs": "sum", "backlog": "gauge",
                     "lat": "histogram"}
    hist = next(m for m in metrics if _pb_fields(m, 1)[0] == b"lat")
    (hbody,) = _pb_fields(hist, 9)
    (point,) = _pb_fields(hbody, 1)
    (count,) = _pb_fields(point, 4)                  # fixed64 count
    assert struct.unpack("<Q", count)[0] == 4
    (bucket_counts,) = _pb_fields(point, 6)          # packed fixed64
    counts = struct.unpack(f"<{len(bucket_counts) // 8}Q", bucket_counts)
    assert sum(counts) == 4 and len(counts) == len(h.counts)
    (bounds,) = _pb_fields(point, 7)                 # packed double
    n_bounds = len(bounds) // 8
    assert n_bounds == len(counts) - 1               # +Inf excluded


async def test_otlp_sink_encoding_selection(monkeypatch):
    """encoding="protobuf" flips the Content-Type; unknown encodings are
    rejected; and when google.protobuf is absent the sink degrades to
    JSON with a warning instead of dying (the binary path is an
    optimization, never a dependency)."""
    from orleans_tpu.observability import export

    sink = OtlpSink("http://127.0.0.1:9/v1/traces", encoding="protobuf")
    assert sink.encoding == "protobuf"
    assert sink.content_type == "application/x-protobuf"
    body = sink._encode(_mk_span_dicts(2))
    assert _pb_fields(body, 1)  # valid protobuf, not JSON
    await sink.aclose(flush=False)

    json_sink = OtlpSink("http://127.0.0.1:9/v1/traces")
    assert json_sink.content_type == "application/json"
    import json as _json
    assert _json.loads(json_sink._encode(_mk_span_dicts(1)))
    await json_sink.aclose(flush=False)

    with pytest.raises(ValueError):
        OtlpSink("http://127.0.0.1:9/v1/traces", encoding="msgpack")

    monkeypatch.setattr(export, "_HAS_PROTOBUF", False)
    degraded = OtlpSink("http://127.0.0.1:9/v1/traces",
                        encoding="protobuf")
    assert degraded.encoding == "json"
    assert degraded.content_type == "application/json"
    await degraded.aclose(flush=False)
